"""UMAP — API parity with the reference's ``spark_rapids_ml.umap``
(``/root/reference/python/src/spark_rapids_ml/umap.py``, 1327 LoC).

Architecture parity:
* fit is **single-host** (the reference coalesces to one partition,
  ``umap.py:830-909``), optionally on a ``sample_fraction`` subsample;
* the model holds the embedding + raw training data (the reference
  broadcasts both in chunks, ``umap.py:873-895``); transform is
  embarrassingly parallel over query batches (``umap.py:1149-1230``);
* the 18-param surface matches ``umap.py:148-341``.

Compute path (``ops/umap_kernels.py``): brute-force kNN graph → fuzzy
simplicial set (host scipy symmetrization) → spectral/random init →
negative-sampling SGD, jitted end-to-end. Transform embeds new points by
membership-weighted neighbor averaging refined with the same SGD against
the frozen training embedding (cuML's transform algorithm).
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core import _TpuEstimator, _TpuModel
from ..data.dataframe import DataFrame
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasOutputCol,
    TypeConverters,
    _mk,
)
from ..ops.ivf_kernels import (
    build_ivf_index,
    ivf_search,
    resolve_ann_params,
    select_graph_engine,
)
from ..ops.kmeans_kernels import pairwise_sq_dists
from ..ops.knn_kernels import _tile_top_k, resolve_knn_topk
from ..parallel.mesh import allgather_ragged_rows
from ..ops.umap_kernels import (
    build_row_adjacency,
    categorical_simplicial_set_intersection,
    default_n_epochs,
    find_ab_params,
    fuzzy_simplicial_set,
    membership_strengths,
    optimize_embedding_rows,
    smooth_knn_dist,
    spectral_init,
)
from ..ops.umap_pallas import (
    default_rng_mode,
    select_sgd_engine,
    umap_sgd_pallas,
)
from ..runtime import counters, telemetry
from ..runtime.checkpoint import FitCheckpointer, array_digest
from ..runtime.faults import fault_site, fault_sites_active
from ..runtime.scheduler import preempt_point
from ..utils.profiling import StageTimer

_LOGGER = logging.getLogger("spark_rapids_ml_tpu.umap")


def _run_sgd(engine: str, *args: Any, **kwargs: Any) -> jax.Array:
    """Dispatch one SGD run to the selected engine. Both engines share
    the ``optimize_embedding_rows`` signature; the Pallas one adds the
    randomness-source knob (on-chip PRNG on real hardware, the XLA
    stream elsewhere — see ``ops/umap_pallas.py``)."""
    if engine == "pallas":
        return umap_sgd_pallas(*args, rng=default_rng_mode(), **kwargs)
    return optimize_embedding_rows(*args, **kwargs)


def _run_sgd_segmented(
    engine: str,
    emb0: jax.Array,
    row_heads: jax.Array,
    tails_pad: jax.Array,
    p_pad: jax.Array,
    key: jax.Array,
    ckpt: FitCheckpointer,
    **kwargs: Any,
) -> jax.Array:
    """Host-driven segmented SGD: checkpoint/resume over the epoch loop.

    Runs ``TPUML_CKPT_EVERY`` epochs per jitted call via the engines'
    ``epoch_offset``/``epoch_span`` contract — per-epoch RNG and learning
    rate are functions of the ABSOLUTE epoch index, so the segmented walk
    is same-seed equivalent to the single fused ``fori_loop``. Each
    segment boundary is a ``sgd:epoch`` fault site and (when checkpointing
    is on) a snapshot of the embedding + epoch cursor; resume restores
    both and re-enters at the saved absolute epoch. At most two epoch-span
    values occur (the segment and the final remainder), so segmentation
    costs at most one extra compile of the epoch loop.
    """
    n_epochs = int(kwargs["n_epochs"])
    seg = ckpt.every if ckpt.enabled else 1
    e = 0
    emb = emb0
    resumed = ckpt.load()
    if resumed is not None:
        e, arrays, _ = resumed
        emb = jnp.asarray(arrays["embedding"])
        counters.bump("resumed_fits")
        counters.note("resumed_from", e)
    while e < n_epochs:
        fault_site("sgd:epoch")
        span = min(seg, n_epochs - e)
        emb = _run_sgd(
            engine,
            emb,
            emb,
            row_heads,
            tails_pad,
            p_pad,
            key,
            epoch_offset=e,
            epoch_span=span,
            **kwargs,
        )
        e += span
        ckpt.maybe_save(e, {"embedding": np.asarray(emb)})
        preempt_point(ckpt, e, lambda: {"embedding": np.asarray(emb)})
    ckpt.clear()
    return emb


@functools.partial(jax.jit, static_argnames=("k", "qchunk", "topk_impl"))
def knn_brute(
    X: jax.Array, Xq: jax.Array, *, k: int, qchunk: int = 4096,
    topk_impl: str = "auto",
):
    """Single-host brute-force kNN: (dists ascending, indices), (nq, k).

    Top-k selection routes through ``ops.knn_kernels._tile_top_k`` so the
    ``TPUML_KNN_TOPK`` escape hatch applies here too (callers pass
    ``topk_impl=resolve_knn_topk()``); the default PartialReduce path at
    recall_target=1.0 is exact and much faster than full-sort ``top_k``.
    """
    nq = Xq.shape[0]
    pad = (-nq) % qchunk
    Xqp = jnp.pad(Xq, ((0, pad), (0, 0)))
    chunks = Xqp.reshape(-1, qchunk, Xq.shape[1])

    def body(_, xc):
        d2 = pairwise_sq_dists(xc, X)
        negd, idx = _tile_top_k(-d2, k, topk_impl)
        return None, (-negd, idx)

    _, (d2, idx) = lax.scan(body, None, chunks)
    d2 = d2.reshape(-1, k)[:nq]
    idx = idx.reshape(-1, k)[:nq]
    return jnp.sqrt(jnp.maximum(d2, 0.0)), idx


@functools.partial(jax.jit, static_argnames=("k",))
def drop_self_column(dists: jax.Array, idx: jax.Array, *, k: int):
    """Remove the self entry from a (n, k+1) self-kNN result, on device.

    Column semantics are identical to the historical host path (fetch k+1,
    drop the FIRST index-match column, else the last column): with
    duplicate rows top-k tie-breaking can put self anywhere in the tie
    run, so dropping column 0 would discard a real neighbor and keep a
    self-loop. Keeping the drop on device means the graph stage transfers
    the (n, k) result once instead of round-tripping the full (n, k+1)
    arrays through numpy for a boolean-mask reshape.

    Returns (dists (n, k), idx (n, k)) — a pure order-preserving gather of
    the input values, so the kept entries are bit-identical to the host
    formulation's.
    """
    n = idx.shape[0]
    rows = jnp.arange(n, dtype=idx.dtype)[:, None]
    self_mask = idx == rows
    has_self = self_mask.any(axis=1)
    drop_col = jnp.where(has_self, jnp.argmax(self_mask, axis=1), k)
    # column j of the output reads input column j, shifted past the
    # dropped one: j + (j >= drop_col)
    cols = jnp.arange(k, dtype=jnp.int32)[None, :]
    src = cols + (cols >= drop_col[:, None]).astype(jnp.int32)
    return (
        jnp.take_along_axis(dists, src, axis=1),
        jnp.take_along_axis(idx, src, axis=1),
    )


class UMAPClass:
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # all params are dedicated, names identical on both sides (reference
        # ``umap.py:92-94``); identity-mapped so ``_set_params`` syncs them
        # into ``_tpu_params``
        return {
            name: name
            for name in (
                "n_neighbors", "n_components", "metric", "n_epochs",
                "learning_rate", "init", "min_dist", "spread",
                "set_op_mix_ratio", "local_connectivity", "repulsion_strength",
                "negative_sample_rate", "transform_queue_size", "a", "b",
                "random_state",
            )
        }

    @classmethod
    def _param_value_mapping(cls) -> Dict[str, Callable[[Any], Any]]:
        def _metric(v: str) -> str:
            if v != "euclidean":
                raise ValueError(
                    f"Only the euclidean metric is supported, got {v!r}"
                )
            return v

        def _init(v: str) -> str:
            if v not in ("spectral", "random"):
                raise ValueError(f"Unsupported init: {v!r}")
            return v

        return {"metric": _metric, "init": _init}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        # reference ``umap.py:96-118`` (cuML defaults)
        return {
            "n_neighbors": 15,
            "n_components": 2,
            "metric": "euclidean",
            "n_epochs": None,
            "learning_rate": 1.0,
            "init": "spectral",
            "min_dist": 0.1,
            "spread": 1.0,
            "set_op_mix_ratio": 1.0,
            "local_connectivity": 1.0,
            "repulsion_strength": 1.0,
            "negative_sample_rate": 5,
            "transform_queue_size": 4.0,
            "a": None,
            "b": None,
            "random_state": None,
        }


class _UMAPParams(HasFeaturesCol, HasFeaturesCols, HasLabelCol, HasOutputCol):
    n_neighbors = _mk("n_neighbors", "local neighborhood size", TypeConverters.toFloat)
    n_components = _mk("n_components", "embedding dimension", TypeConverters.toInt)
    metric = _mk("metric", "distance metric (euclidean)", TypeConverters.toString)
    n_epochs = _mk("n_epochs", "optimization epochs", TypeConverters.toInt)
    learning_rate = _mk("learning_rate", "initial SGD alpha", TypeConverters.toFloat)
    init = _mk("init", "embedding init: spectral | random", TypeConverters.toString)
    min_dist = _mk("min_dist", "min embedded point spacing", TypeConverters.toFloat)
    spread = _mk("spread", "embedded cluster scale", TypeConverters.toFloat)
    set_op_mix_ratio = _mk("set_op_mix_ratio", "union/intersection mix", TypeConverters.toFloat)
    local_connectivity = _mk("local_connectivity", "assumed local connectivity", TypeConverters.toFloat)
    repulsion_strength = _mk("repulsion_strength", "negative-sample gamma", TypeConverters.toFloat)
    negative_sample_rate = _mk("negative_sample_rate", "negatives per positive", TypeConverters.toInt)
    transform_queue_size = _mk("transform_queue_size", "transform search factor (ignored: search is exact)", TypeConverters.toFloat)
    a = _mk("a", "curve param a (None: from min_dist/spread)", TypeConverters.toFloat)
    b = _mk("b", "curve param b (None: from min_dist/spread)", TypeConverters.toFloat)
    random_state = _mk("random_state", "random seed", TypeConverters.toInt)
    sample_fraction = _mk("sample_fraction", "fit subsample fraction", TypeConverters.toFloat)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            n_neighbors=15.0,
            n_components=2,
            metric="euclidean",
            learning_rate=1.0,
            init="spectral",
            min_dist=0.1,
            spread=1.0,
            set_op_mix_ratio=1.0,
            local_connectivity=1.0,
            repulsion_strength=1.0,
            negative_sample_rate=5,
            transform_queue_size=4.0,
            sample_fraction=1.0,
            outputCol="embedding",
        )

    def getNNeighbors(self) -> float:
        return self.getOrDefault("n_neighbors")

    def setNNeighbors(self, value: float) -> "_UMAPParams":
        self._set_params(n_neighbors=value)  # type: ignore[attr-defined]
        return self

    def getNComponents(self) -> int:
        return self.getOrDefault("n_components")

    def setNComponents(self, value: int) -> "_UMAPParams":
        self._set_params(n_components=value)  # type: ignore[attr-defined]
        return self

    def getSampleFraction(self) -> float:
        return self.getOrDefault("sample_fraction")

    def setSampleFraction(self, value: float) -> "_UMAPParams":
        self._set_params(sample_fraction=value)  # type: ignore[attr-defined]
        return self

    def setOutputCol(self, value: str) -> "_UMAPParams":
        self._set(outputCol=value)
        return self

    def setFeaturesCol(self, value: Union[str, List[str]]) -> "_UMAPParams":
        if isinstance(value, (list, tuple)):
            self._set(featuresCols=list(value))
        else:
            self._set(featuresCol=value)
        return self

    def _resolve_features(self, df: DataFrame) -> np.ndarray:
        from ..core import _resolve_features_f32

        return _resolve_features_f32(self, df)


class UMAP(UMAPClass, _TpuEstimator, _UMAPParams):
    """``UMAP(n_components=2).fit(df)`` — unsupervised manifold embedding
    (reference ``umap.py:620-957``)."""

    def __init__(self, **kwargs: Any) -> None:
        _TpuEstimator.__init__(self)
        _UMAPParams.__init__(self)
        self._set_params(**kwargs)

    def fit(self, dataset: DataFrame, params: Optional[Dict[Any, Any]] = None) -> "UMAPModel":
        if params:
            est = self.copy()
            self._copy_tpu_params(est)
            est._set_params(**{p.name if hasattr(p, "name") else p: v for p, v in params.items()})
            return est.fit(dataset)
        # UMAP overrides fit() and skips the core per-fit loop, so it
        # opens the root telemetry span itself (same name shape as
        # core._fit_internal_x64scoped)
        with telemetry.span("UMAP.fit"):
            return self._fit_umap(dataset)

    def _fit_umap(self, dataset: DataFrame) -> "UMAPModel":
        from ..parallel.context import ensure_distributed

        ensure_distributed()  # idempotent (package import already ran it)
        res_base = counters.snapshot()
        seed = int(self._tpu_params.get("random_state") or 0)
        frac = float(self.getSampleFraction())
        df = dataset if frac >= 1.0 else dataset.sample(frac, seed=seed)
        X = self._resolve_features(df)
        y_labels: Optional[np.ndarray] = None
        if jax.process_count() > 1:
            # UMAP fit is single-node by design (the reference coalesces to
            # ONE partition, ``umap.py:841-850``): gather every process's
            # (sampled) partition so all ranks fit the same model on the
            # full dataset — fitting each rank's local slice would silently
            # produce divergent models
            X = allgather_ragged_rows(X)
        if self.isDefined("labelCol") and self.isSet("labelCol"):
            # supervised fit (reference delegates to cuML fit(X, y=labels),
            # ``umap.py:941-947``): labels sharpen the fuzzy set below
            label_col = self.getOrDefault("labelCol")
            if label_col not in df:
                raise ValueError(
                    f"labelCol {label_col!r} not found in dataset columns "
                    f"{df.columns}"
                )
            y_labels = np.asarray(df.column(label_col)).astype(np.int64)
            if jax.process_count() > 1:
                y_labels = allgather_ragged_rows(y_labels[:, None]).ravel()
        n = X.shape[0]
        k = int(self._tpu_params.get("n_neighbors", 15))
        if k >= n:
            raise ValueError(f"n_neighbors={k} must be < number of rows {n}")

        # stage decomposition (graph / init / sgd) feeds the bench entry
        # and the debug log; device work materializes inside its stage so
        # async dispatch cannot smear across the split
        timer = StageTimer("umap.fit")

        # graph-engine dispatch (TPUML_UMAP_GRAPH, gate + warn-fallback):
        # the exact brute-force sweep vs the IVF-Flat approximate engine
        # (ops/ivf_kernels.py). Resolved OUTSIDE the jitted kernels; k+1
        # because the self entry is fetched then dropped.
        graph_engine = select_graph_engine(n, k + 1)
        ann_nlist = ann_nprobe = None
        with timer.stage("graph"):
            # 1) kNN graph: fetch k+1 and drop the SELF entry on device
            # (see drop_self_column for the tie-run column semantics)
            Xd = jnp.asarray(X)
            if graph_engine == "ivf":
                ann_nlist, ann_nprobe = resolve_ann_params(n)
                ivf_index = build_ivf_index(X, nlist=ann_nlist, seed=seed)
                d2, idx = ivf_search(
                    Xd, ivf_index, k=k + 1, nprobe=ann_nprobe,
                    topk_impl=resolve_knn_topk(),
                )
                dists = jnp.sqrt(jnp.maximum(d2, 0.0))
            else:
                dists, idx = knn_brute(
                    Xd, Xd, k=k + 1, topk_impl=resolve_knn_topk()
                )
            knn_d_dev, knn_i_dev = drop_self_column(dists, idx, k=k)
            knn_i = np.asarray(knn_i_dev)
            knn_d = np.asarray(knn_d_dev)

            # 2) fuzzy simplicial set (+ categorical label intersection
            # when supervised)
            heads, tails, weights = fuzzy_simplicial_set(
                knn_i,
                knn_d,
                float(self._tpu_params.get("local_connectivity", 1.0)),
                float(self._tpu_params.get("set_op_mix_ratio", 1.0)),
            )
            if y_labels is not None:
                heads, tails, weights = categorical_simplicial_set_intersection(
                    heads, tails, weights, y_labels, n
                )

        with timer.stage("init"):
            # 3) curve params + init
            a = self._tpu_params.get("a")
            b = self._tpu_params.get("b")
            if a is None or b is None:
                a, b = find_ab_params(
                    float(self._tpu_params.get("spread", 1.0)),
                    float(self._tpu_params.get("min_dist", 0.1)),
                )
            n_comp = int(self._tpu_params.get("n_components", 2))
            if self._tpu_params.get("init", "spectral") == "spectral":
                emb0 = spectral_init(heads, tails, weights, n, n_comp, seed)
            else:
                emb0 = (
                    np.random.default_rng(seed)
                    .uniform(-10, 10, size=(n, n_comp))
                    .astype(np.float32)
                )

        with timer.stage("sgd"):
            # 4) SGD over CSR-padded rows (``build_row_adjacency``):
            # head-only updates with cuML's directed-symmetric semantics;
            # the row count is bucketed inside the builder so same-bucket
            # fits reuse the compiled epoch loop (an unpadded call
            # recompiles on EVERY fit — ~60 s measured at the 64k bench
            # shape, as long as the SGD). Graduate the row bucket for
            # small fits so they don't spend most SGD work on inert
            # padding.
            row_bucket = 4096 if n >= 4096 else 256
            # K=24 measured best at the bench shape (9.2 vs 10.7 ms/epoch
            # at K=32): fewer inert padding slots than 32, fewer split
            # rows than 16
            row_heads, tails_pad, p_pad = build_row_adjacency(
                heads, tails, weights, n, K=24, row_bucket=row_bucket
            )
            n_epochs = self._tpu_params.get("n_epochs") or default_n_epochs(n)
            neg_rate = int(self._tpu_params.get("negative_sample_rate", 5))
            # engine dispatch (TPUML_UMAP_OPT, probe-gated): the
            # VMEM-resident Pallas kernel vs the jitted XLA loop
            engine = select_sgd_engine(n, tails_pad.shape[1], n_comp, neg_rate)
            emb0 = jnp.asarray(emb0)
            gamma_v = float(self._tpu_params.get("repulsion_strength", 1.0))
            alpha_v = float(self._tpu_params.get("learning_rate", 1.0))
            sgd_kwargs: Dict[str, Any] = dict(
                n_epochs=int(n_epochs),
                a=float(a),
                b=float(b),
                gamma=gamma_v,
                initial_alpha=alpha_v,
                negative_sample_rate=neg_rate,
                self_table=True,
            )
            sgd_args = (
                jnp.asarray(row_heads),
                jnp.asarray(tails_pad),
                jnp.asarray(p_pad),
                jax.random.PRNGKey(seed),
            )
            # checkpoint identity: everything the epoch sequence depends
            # on, with array inputs content-digested (same seed + same
            # graph => same stream; anything else must cold-start)
            ckpt = FitCheckpointer.from_env(
                "umap",
                {
                    "seed": seed,
                    "n_epochs": int(n_epochs),
                    "a": float(a),
                    "b": float(b),
                    "gamma": gamma_v,
                    "alpha": alpha_v,
                    "neg": neg_rate,
                    "engine": engine,
                    "n": n,
                    "n_comp": n_comp,
                    "emb0": array_digest(emb0),
                    "row_heads": array_digest(row_heads),
                    "tails": array_digest(tails_pad),
                    "p": array_digest(p_pad),
                },
            )
            if ckpt.enabled or fault_sites_active("sgd:epoch"):
                # host-segmented epochs: checkpointable/faultable, same
                # seed-equivalence as the fused loop (absolute-epoch RNG)
                emb = _run_sgd_segmented(
                    engine, emb0, *sgd_args, ckpt, **sgd_kwargs
                )
            else:
                # clean path: one fused fori_loop call, unchanged
                emb = _run_sgd(engine, emb0, emb0, *sgd_args, **sgd_kwargs)
            emb_host = np.asarray(emb, dtype=np.float32)

        model = UMAPModel(
            embedding_=emb_host,
            raw_data_=X,
            a=float(a),
            b=float(b),
        )
        self._copyValues(model)
        self._copy_tpu_params(model)
        stages = dict(timer.totals)
        timer.log_summary(_LOGGER)
        sgd_s = stages.get("sgd", 0.0)
        # non-persisted fit provenance for the bench/debug surface (the
        # rf transform_engine analog): which SGD engine ran and where the
        # fit wall-clock went
        model._fit_report = {
            "graph_seconds": round(stages.get("graph", 0.0), 4),
            "init_seconds": round(stages.get("init", 0.0), 4),
            "sgd_seconds": round(sgd_s, 4),
            "epoch_ms": round(sgd_s / max(int(n_epochs), 1) * 1e3, 3),
            "sgd_engine": engine,
            "graph_engine": graph_engine,
        }
        if graph_engine == "ivf":
            # the bench recall probe rebuilds the (deterministic) index
            # from exactly these parameters
            model._fit_report["ann_nlist"] = ann_nlist
            model._fit_report["ann_nprobe"] = ann_nprobe
        # UMAP overrides fit() and skips the core per-fit loop, so attach
        # the resilience delta here (same contract as core._fit_internal)
        model._resilience_report = counters.delta_since(res_base)
        if model._resilience_report:
            _LOGGER.info(
                "resilience events during fit: %s", model._resilience_report
            )
        return model

    def _get_tpu_fit_func(self, dataset: DataFrame):  # pragma: no cover
        raise NotImplementedError("UMAP overrides fit directly")

    def _create_model(self, result: Dict[str, Any]):  # pragma: no cover
        raise NotImplementedError("UMAP overrides fit directly")


class UMAPModel(UMAPClass, _TpuModel, _UMAPParams):
    """Reference ``umap.py:1118-1259``. Holds (embedding, raw data); transform
    embeds new points against the frozen training embedding."""

    def __init__(self, **attrs: Any) -> None:
        _TpuModel.__init__(self, **attrs)
        _UMAPParams.__init__(self)

    @property
    def embedding_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["embedding_"])

    @property
    def embedding(self) -> List[List[float]]:
        return self.embedding_.tolist()

    @property
    def raw_data_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["raw_data_"])

    def _out_cols(self) -> List[str]:
        return [self.getOrDefault("outputCol")]

    def _refine_engine(self, n_tab: int, K: int, C: int, neg: int) -> str:
        """SGD engine for the transform refine pass, memoized per config
        (and per ``TPUML_UMAP_OPT`` value, so tests flipping the env var
        are not pinned to a stale choice): the lowering probe behind
        ``select_sgd_engine`` AOT-compiles on first use — repeated
        transform micro-batches must not re-enter it."""
        from ..ops.umap_pallas import resolve_umap_opt

        cache = getattr(self, "_sgd_engine_cache", None)
        if cache is None:
            cache = self._sgd_engine_cache = {}
        key = (n_tab, K, C, neg, resolve_umap_opt())
        if key not in cache:
            cache[key] = select_sgd_engine(n_tab, K, C, neg)
        return cache[key]

    def _transform_ivf_index(self, k: int):
        """IVF index over the frozen training rows for the transform kNN,
        memoized per (nlist, nprobe, seed): the build (sample + Lloyd +
        balance) runs once, then every transform micro-batch reuses the
        device-resident arrays. Returns ``(index, nprobe)`` or ``None``
        when the engine resolution picks the exact sweep for this config
        (``TPUML_UMAP_GRAPH`` participates in the memo key so tests
        flipping the env are not pinned to a stale choice)."""
        from ..ops.ivf_kernels import resolve_umap_graph

        n_train = int(self.raw_data_.shape[0])
        if select_graph_engine(n_train, k) != "ivf":
            return None
        nlist, nprobe = resolve_ann_params(n_train)
        seed = int(self._tpu_params.get("random_state") or 0)
        cache = getattr(self, "_ivf_index_cache", None)
        if cache is None:
            cache = self._ivf_index_cache = {}
        key = (nlist, nprobe, seed, resolve_umap_graph())
        if key not in cache:
            # the span is the one-build-many-queries witness: serving and
            # the tests assert its count stays 1 across repeated
            # transforms against the same frozen training rows
            with telemetry.span("umap.ivf_build", nlist=nlist):
                cache[key] = build_ivf_index(
                    self.raw_data_, nlist=nlist, seed=seed
                )
        return cache[key], nprobe

    def _get_tpu_transform_func(
        self, dataset: Optional[DataFrame] = None
    ) -> Callable[[np.ndarray], Dict[str, np.ndarray]]:
        out_col = self.getOrDefault("outputCol")
        k = int(self._tpu_params.get("n_neighbors", 15))
        k = min(k, int(self.raw_data_.shape[0]))
        a = float(self._model_attributes["a"])
        b = float(self._model_attributes["b"])
        seed = int(self._tpu_params.get("random_state") or 0)
        n_epochs = int(
            self._tpu_params.get("n_epochs")
            or default_n_epochs(int(self.raw_data_.shape[0]))
        )
        refine = max(n_epochs // 3, 10)
        lc = float(self._tpu_params.get("local_connectivity", 1.0))
        gamma = float(self._tpu_params.get("repulsion_strength", 1.0))
        neg = int(self._tpu_params.get("negative_sample_rate", 5))
        alpha = float(self._tpu_params.get("learning_rate", 1.0))
        # memoized on the model: the closure hoists the frozen training
        # table + embedding to the device ONCE; a per-call rebuild would
        # re-stage both arrays and retrace every jitted program on every
        # transform (graph-engine env knobs resolve INSIDE the returned
        # fn per batch, so they stay live and need no key entry)
        return self._memoized_transform_fn(
            ("umap", out_col, k, a, b, seed, refine, lc, gamma, neg, alpha),
            lambda: self._build_transform_fn(
                out_col, k, a, b, seed, refine, lc, gamma, neg, alpha
            ),
        )

    def _build_transform_fn(
        self,
        out_col: str,
        k: int,
        a: float,
        b: float,
        seed: int,
        refine: int,
        lc: float,
        gamma: float,
        neg: int,
        alpha: float,
    ) -> Callable[[np.ndarray], Dict[str, np.ndarray]]:
        train_X = jnp.asarray(self.raw_data_)
        train_emb = jnp.asarray(self.embedding_)
        n_comp = int(train_emb.shape[1])

        def _fn(Xb: np.ndarray) -> Dict[str, np.ndarray]:
            nq = Xb.shape[0]
            # same graph-engine dispatch as fit: the transform kNN runs
            # against the frozen training rows, so the memoized IVF index
            # amortizes across micro-batches (None = exact sweep)
            ivf = self._transform_ivf_index(k)
            if ivf is not None:
                index, nprobe = ivf
                d2, idx = ivf_search(
                    jnp.asarray(Xb, jnp.float32), index, k=k,
                    nprobe=nprobe, topk_impl=resolve_knn_topk(),
                )
                dists = jnp.sqrt(jnp.maximum(d2, 0.0))
            else:
                dists, idx = knn_brute(
                    train_X, jnp.asarray(Xb, jnp.float32), k=k,
                    topk_impl=resolve_knn_topk(),
                )
            rho, sigma = smooth_knn_dist(dists, lc)
            w = membership_strengths(dists, rho, sigma)       # (nq, k)
            wn = w / jnp.maximum(w.sum(axis=1, keepdims=True), 1e-12)
            emb0 = jnp.einsum("qk,qkc->qc", wn, train_emb[idx])
            # query q's row adjacency is exactly its k membership edges:
            # already CSR-padded shape (nq, k), one row per query
            row_heads = jnp.arange(nq, dtype=jnp.int32)
            p_pad = w / jnp.maximum(w.max(), 1e-12)
            # refine against the FROZEN training table: same engine
            # dispatch as fit (the Pallas kernel keeps train_emb
            # VMEM-resident across each refine epoch)
            engine = self._refine_engine(
                int(train_emb.shape[0]), k, n_comp, neg
            )
            emb = _run_sgd(
                engine,
                emb0,
                train_emb,
                row_heads,
                idx.astype(jnp.int32),
                p_pad,
                jax.random.PRNGKey(seed),
                n_epochs=refine,
                a=a,
                b=b,
                gamma=gamma,
                initial_alpha=alpha,
                negative_sample_rate=neg,
                self_table=False,
            )
            self._transform_report = {
                "sgd_engine": engine,
                "refine_epochs": refine,
                "graph_engine": "ivf" if ivf is not None else "exact",
            }
            return {out_col: np.asarray(emb)}

        return _fn
