#!/usr/bin/env bash
# Reference-style benchmark wrapper (the analog of
# /root/reference/python/run_benchmark.sh and
# databricks/run_benchmark.sh:44-135): run every workload through
# benchmark_runner.py at a configurable scale.
#
#   ./run_benchmark.sh [cpu|tpu] [num_rows] [num_cols] [report.csv]
#
# Defaults mirror the reference's local smoke scale (5000 x 3000,
# run_benchmark.sh:66-68); the full methodology scale is 1M x 3000.
set -euo pipefail
cd "$(dirname "$0")"

PLATFORM="${1:-cpu}"
NUM_ROWS="${2:-5000}"
NUM_COLS="${3:-3000}"
REPORT="${4:-}"

REPORT_ARGS=()
if [ -n "$REPORT" ]; then
    REPORT_ARGS=(--report_path "$REPORT")
fi

run() {
    echo "== $1 =="
    shift
    python benchmark_runner.py "$@" ${REPORT_ARGS[@]+"${REPORT_ARGS[@]}"}
}

COMMON=(--platform "$PLATFORM" --num_rows "$NUM_ROWS" --num_cols "$NUM_COLS")

# workload configs follow the reference methodology
# (databricks/run_benchmark.sh:44-135)
run kmeans   kmeans   "${COMMON[@]}" --k 1000 --max_iter 30 --tol 1e-20 --init random
run pca      pca      "${COMMON[@]}" --k 3
run linreg   linear_regression "${COMMON[@]}"
run linreg-elastic linear_regression "${COMMON[@]}" --regParam 0.00001 --elasticNetParam 0.5
run linreg-ridge   linear_regression "${COMMON[@]}" --regParam 0.00001
run rf-cls   random_forest_classifier "${COMMON[@]}" --numTrees 50 --maxDepth 13 --maxBins 128
run rf-reg   random_forest_regressor  "${COMMON[@]}" --numTrees 30 --maxDepth 6 --maxBins 128
run logreg   logistic_regression "${COMMON[@]}" --maxIter 200 --tol 1e-30 --regParam 0.00001
