"""TPU006 — Pallas block shapes with an unaligned minor dimension.

The TPU vector unit operates on (8, 128) tiles; a BlockSpec (or
``pltpu.PrefetchScalarGridSpec`` block shape) whose *minor* (last)
dimension is a literal not divisible by 128 forces the Mosaic compiler
into padded, partially-masked lanes — or fails to lower outright.
Symbolic dims (``bn``, ``feat_pad``, …) are assumed already rounded by
the caller (the repo rounds with ``_round_up(x, 128)`` helpers);
only literal offenders are flagged.

Exempt: 0-d/1-element scalar specs and specs whose ``memory_space`` is
SMEM/ANY — scalars don't live in lanes.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from .core import Finding, SourceFile, dotted_name

CODE = "TPU006"
NAME = "lane-align"

_BLOCKSPEC_NAMES = ("pl.BlockSpec", "BlockSpec", "pallas.BlockSpec")
_SMEM_MARKERS = ("SMEM", "ANY", "smem")
LANE = 128


def _shape_tuple(node: ast.AST) -> Optional[List[ast.AST]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return list(node.elts)
    return None


def _minor_literal(elts: List[ast.AST]) -> Optional[int]:
    """Value of the last dim if it's an int literal, else None."""
    if not elts:
        return None
    last = elts[-1]
    if isinstance(last, ast.Constant) and isinstance(last.value, int):
        return last.value
    return None


def _spec_is_scalar_space(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "memory_space":
            src = ast.dump(kw.value)
            return any(m in src for m in _SMEM_MARKERS)
    return False


def _block_shape_arg(call: ast.Call) -> Optional[List[ast.AST]]:
    """The block-shape tuple of a BlockSpec call, positional or kw."""
    for kw in call.keywords:
        if kw.arg == "block_shape":
            return _shape_tuple(kw.value)
    # modern signature: BlockSpec(block_shape, index_map); legacy:
    # BlockSpec(index_map, block_shape) — try any tuple positional.
    for arg in call.args:
        t = _shape_tuple(arg)
        if t is not None:
            return t
    return None


def check_file(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if fn not in _BLOCKSPEC_NAMES:
            continue
        if _spec_is_scalar_space(node):
            continue
        elts = _block_shape_arg(node)
        if elts is None or len(elts) < 2:
            # 0-d/1-d scalar-ish specs: lane tiling doesn't apply the
            # same way; the repo's (1, 1) specs are SMEM scalars.
            continue
        minor = _minor_literal(elts)
        if minor is None:
            continue
        if minor == 1 and all(
            isinstance(e, ast.Constant) and e.value == 1 for e in elts
        ):
            continue  # (1, 1) scalar spec
        if minor % LANE != 0:
            yield sf.finding(
                CODE, node,
                f"BlockSpec minor dimension {minor} is not a multiple of "
                f"{LANE} — TPU lanes are {LANE}-wide, so this block is "
                f"padded and partially masked on every access",
                f"round the minor dim up to a multiple of {LANE} (pad the "
                f"array) or derive it from a _round_up(x, {LANE}) helper",
            )
