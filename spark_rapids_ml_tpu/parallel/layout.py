"""Named ``PartitionSpec`` layout over the ``(dp, mp)`` mesh.

Single source of truth for how framework arrays map onto the 2-D device
mesh (``parallel/mesh.py``): rows of the design matrix shard over ``dp``,
model-axis blocks (Gram column blocks, centroid blocks, IVF list shards)
shard over ``mp``, scalars and small solver state replicate. Estimator
and ops code must take specs from here — ``tpuml_lint`` rule TPU009
rejects inline ``PartitionSpec(...)`` construction outside ``parallel/``,
so the axis-name contract lives in exactly one module.

Every spec is valid on ANY ``(dp, mp)`` mesh: with mp=1 the mp-named
specs degenerate to single-device-axis shardings and the compiled
programs are identical to the historical 1-D ones (the defaults-inert
contract asserted by ``tests/test_mesh2d.py``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from jax.sharding import PartitionSpec

from .mesh import DP_AXIS, MP_AXIS

Axis = str


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs for framework arrays over ``(dp, mp)``."""

    dp_axis: Axis = DP_AXIS
    mp_axis: Axis = MP_AXIS

    def rows(self) -> PartitionSpec:
        """Row-sharded inputs: dim 0 over dp, replicated over mp — the
        design matrix, masks, labels, weights, per-row outputs."""
        return PartitionSpec(self.dp_axis)

    def replicated(self) -> PartitionSpec:
        """Fully replicated: scalars, reduced statistics, small solver
        state (means, coefficients, centroid tables on the 1-D path)."""
        return PartitionSpec()

    def cols(self) -> PartitionSpec:
        """Column-blocked square accumulators: dim 1 over mp — the
        SUMMA-style Gram/covariance blocks (d, d/mp per device)."""
        return PartitionSpec(None, self.mp_axis)

    def feature_blocks(self) -> PartitionSpec:
        """Feature-sharded parameter blocks: dim 0 over mp — per-feature
        parameter/state vectors split along the model axis."""
        return PartitionSpec(self.mp_axis)

    def centroid_blocks(self) -> PartitionSpec:
        """Centroid-sharded tables: dim 0 (k axis) over mp."""
        return PartitionSpec(self.mp_axis)

    def list_blocks(self) -> PartitionSpec:
        """List-sharded IVF grouped arrays: dim 0 (nlist*cap rows,
        list-major) over mp."""
        return PartitionSpec(self.mp_axis)

    def rows_and_cols(self) -> PartitionSpec:
        """Fully 2-D sharded matrices: rows over dp AND columns over mp
        (wide-feature design matrices in the multichip dryrun)."""
        return PartitionSpec(self.dp_axis, self.mp_axis)


#: The framework-wide layout instance. Import this — constructing a
#: private SpecLayout is only for tests exercising alternate axis names.
LAYOUT = SpecLayout()

#: Named registry for docs/tests: every canonical spec by name.
_REGISTRY: Dict[str, PartitionSpec] = {
    "rows": LAYOUT.rows(),
    "replicated": LAYOUT.replicated(),
    "cols": LAYOUT.cols(),
    "feature_blocks": LAYOUT.feature_blocks(),
    "centroid_blocks": LAYOUT.centroid_blocks(),
    "list_blocks": LAYOUT.list_blocks(),
    "rows_and_cols": LAYOUT.rows_and_cols(),
}


def spec(name: str) -> PartitionSpec:
    """Resolve a canonical spec by registry name; raises ``KeyError``
    listing the known names on a miss."""
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown layout spec {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


def spec_names() -> Dict[str, PartitionSpec]:
    """A copy of the full name -> spec registry (docs/tests)."""
    return dict(_REGISTRY)
