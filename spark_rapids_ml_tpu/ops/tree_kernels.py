"""RandomForest device kernels: histogram tree building + batched inference.

TPU-native replacement for the CUDA decision-tree builder the reference
drives through cuML (``/root/reference/python/src/spark_rapids_ml/tree.py:269-402``
trains a local ``cuml.RandomForest*`` per worker; the builder itself lives in
libcuml). A translation is impossible and undesirable — instead this is an
XGBoost-style **histogram** builder designed for XLA:

* features are quantized once to ``n_bins`` buckets (uint8), so every split
  decision becomes dense integer work with static shapes;
* trees grow **level-wise**: one ``segment_sum`` per feature-chunk builds the
  (node, feature, bin, stat) histogram, a cumulative-sum scan turns it into
  left/right sufficient statistics for every candidate threshold, and an
  argmax picks the best split — no per-node recursion, no dynamic shapes;
* the per-level feature chunk size adapts to keep the histogram tile inside
  a fixed HBM budget, so depth-13 × 3000-feature forests (the reference
  benchmark config, ``databricks/run_benchmark.sh:95-112``) fit;
* trees are embarrassingly parallel: each device builds its share of the
  forest on its local row shard (exactly the reference's
  ``_estimators_per_worker`` split, ``tree.py:256-267``) inside one
  ``shard_map`` — zero collectives during growth, matching
  ``_require_nccl_ucx() -> (False, False)`` (``tree.py:416-417``).
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import shard_map
from jax.sharding import Mesh

from ..parallel.layout import LAYOUT
from ..parallel.mesh import DP_AXIS
from ..runtime import autotune, envspec, telemetry

# elements per (F, nodes, bins, stats) histogram tile; bounds peak HBM of the
# deepest level (tile is float32: 1<<22 elems = 16 MiB)
_HIST_BUDGET = 1 << 22

# Histogram strategy cost model. A scatter-add (segment_sum) update costs a
# roughly constant time on TPU (~1e8 updates/s measured — the round-2
# builder's 8.5 s/tree at 131k x 256 x depth 13 is exactly 13 levels of
# n*d*S updates at that rate), while the one-hot-matmul formulation costs
# 2*n_nodes*n_bins MXU flops per update (~5e13 flop/s). The matmul path
# therefore wins while 2*n_nodes*n_bins is below ~5e5 "scatter-equivalent
# flops" — i.e. every level until n_nodes*n_bins ~ 2.5e5 — by up to two
# orders of magnitude at shallow levels. Overridable for re-tuning on other
# chip generations.
_SCATTER_EQ_FLOPS = float(envspec.get("TPUML_RF_SCATTER_EQ_FLOPS"))

# HBM budget for the fused-selection path's residents. Resolved ONCE at
# import (the _SCATTER_EQ_FLOPS pattern — a per-trace env read would be
# silently ignored on jit cache hits): env override, else 3/4 of the
# device's reported memory, else a 16 GB-class default. Device memory is
# process-stable, so deriving it at first use cannot go stale.
_SEL_HBM_BUDGET_ENV = envspec.get("TPUML_RF_SEL_HBM_BUDGET")


def _sel_hbm_budget() -> float:
    if _SEL_HBM_BUDGET_ENV:
        return float(_SEL_HBM_BUDGET_ENV)
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return 0.75 * float(stats["bytes_limit"])
    except Exception:
        pass
    return 12e9


# minimum feature width for the fused-selection histogram kernel: below
# this the word-packed contraction gather is already cheap (~1.6 ms per
# level) and the fused kernel's full-row reads + lane padding cost more
# than they save (measured either way on v5e, round 4). Tests lower it
# to exercise the fused path at interpret-friendly sizes.
_SEL_MIN_DPAD = 1024
def resolve_contract_gather() -> str:
    """Validated subset-extraction strategy from TPUML_RF_CONTRACT_GATHER:
    "auto" (TPU at moderate widths), "on", or "off". Rides the static
    ForestConfig so it participates in the jit cache key — a module flag
    read at trace time would be silently ignored on cache hits."""
    return str(envspec.get("TPUML_RF_CONTRACT_GATHER"))
# rows per matmul accumulation chunk: bounds the (C, n_nodes) node-onehot
# and (C, F*nb) bin-onehot intermediates (C=8192, level 12, F*nb=512:
# 8192*4096*4 = 128 MB node-onehot is the largest, still < HBM noise)
_ROW_CHUNK = 1 << 13


def resolve_hist_strategy() -> str:
    """Validated histogram strategy from the TPUML_RF_FORCE_STRATEGY env
    var (typos must error, not silently fall back to the heuristic).

    "compact" forces the node-contiguous Pallas path on every level where
    its lowering is eligible (TPU, f32 stats, lane-aligned widths) and
    falls back to scatter on levels where it is not — the fused-kernel
    analog of knn's "auto", kept as its own name so "auto" can keep
    meaning "per-level cost model" as strategies evolve."""
    return str(envspec.get("TPUML_RF_FORCE_STRATEGY"))


def _largest_divisor_leq(t: int, b: int) -> int:
    for d in range(min(t, b), 0, -1):
        if t % d == 0:
            return d
    return 1


def resolve_tree_batch(t_group: int, cfg: "ForestConfig", n_rows: int) -> int:
    """Trees advanced per batched level dispatch (1 = sequential builder).

    ``TPUML_RF_TREE_BATCH``: ``off`` pins the sequential per-tree builder,
    an integer pins a batch width, ``auto`` targets the whole dispatch
    group. The result is clamped to (a) a divisor of ``t_group`` — the
    group reshapes to (G, B, 2) key batches — and (b) the widest batch
    whose per-level residents fit the HBM budget: the histogram tile, its
    gain-chain copies, and the per-tree row state (stat weights, routing
    ids, subset-gathered bins) all scale xT, while the per-level strategy
    gates deliberately stay per-tree so batched and sequential builds
    select identical strategies — a precondition of their bit-identity
    (see docs/rf_performance.md).
    """
    raw = str(envspec.get("TPUML_RF_TREE_BATCH")).strip().lower()
    if raw == "off":
        return 1
    tune_key = None
    if raw == "auto":
        want = t_group
        if autotune.active():
            tune_key = autotune.shape_key(
                n=n_rows,
                d=cfg.n_features,
                k=cfg.n_stats,
                dtype="uint8",
                depth=cfg.max_depth,
                group=t_group,
            )
            tuned = autotune.consult("rf_tree_batch", tune_key)
            # a tuned width only applies where it still divides the
            # group — a stale entry from a different tree count falls
            # through to the heuristic rather than breaking the reshape
            if (
                isinstance(tuned, int)
                and 1 <= tuned <= t_group
                and t_group % tuned == 0
            ):
                want = tuned
                tune_key = None  # provenance already filed by consult
    else:
        try:
            want = int(raw)
        except ValueError:
            raise envspec.EnvSpecError(
                f"TPUML_RF_TREE_BATCH={raw!r}: expected 'auto', 'off', or "
                "a positive integer"
            ) from None
        if want < 1:
            raise envspec.EnvSpecError(
                f"TPUML_RF_TREE_BATCH={want}: batch width must be >= 1"
            )
    budget = envspec.get("TPUML_RF_TREE_BATCH_BUDGET")
    budget = float(budget) if budget else _sel_hbm_budget() / 4.0
    subset = cfg.k_features < cfg.n_features
    d_hist = next_pow2(cfg.k_features if subset else max(1, cfg.n_features))
    n_nodes_max = 1 << max(0, cfg.max_depth - 1)
    tile = min(_HIST_BUDGET, n_nodes_max * cfg.n_bins * cfg.n_stats * d_hist)
    per_tree = (
        4 * n_rows * (cfg.n_stats + 4 + (d_hist if subset else 0))
        + 16 * tile
    )
    fit = max(1, int(budget // max(1, per_tree)))
    batch = _largest_divisor_leq(t_group, min(want, fit))
    if tune_key is not None:
        autotune.record_heuristic("rf_tree_batch", tune_key, batch)
    telemetry.record_hbm_estimate("tree_batch", float(per_tree) * batch)
    return batch


class ForestConfig(NamedTuple):
    """Static (compile-time) build configuration."""

    max_depth: int
    n_bins: int
    n_features: int        # real (unpadded) feature count
    n_stats: int           # classification: n_classes; regression: 3
    impurity: str          # "gini" | "entropy" | "variance"
    k_features: int        # features sampled per node (featureSubsetStrategy)
    min_samples_leaf: int  # Spark minInstancesPerNode
    min_info_gain: float   # Spark minInfoGain
    min_samples_split: int
    bootstrap: bool
    # histogram strategy: "auto" (TPU: per-level cost model; CPU: scatter),
    # "matmul", or "scatter". Part of the static config so it participates
    # in the jit cache key (an env var read inside the traced function
    # would be silently ignored on cache hits).
    hist_strategy: str = "auto"
    # subset-extraction strategy: "auto" | "on" | "off" (see
    # resolve_contract_gather); static for the same cache-key reason
    contract_gather: str = "auto"


def max_nodes(max_depth: int) -> int:
    """Full binary tree layout: node i's children are 2i+1 / 2i+2."""
    return (1 << (max_depth + 1)) - 1


# ---------------------------------------------------------------------------
# quantization
# ---------------------------------------------------------------------------


def make_bin_edges(
    X: np.ndarray, n_bins: int, max_sample: int = 131072, seed: int = 0
) -> np.ndarray:
    """Per-feature quantile bin edges (host, on a row subsample).

    Approximate quantile sketching is the standard histogram-GBM approach;
    cuML similarly computes per-feature quantiles on device. Returns
    ``(d, n_bins - 1)`` float32; row x falls in bin ``#{edges <= x}``.
    """
    n = X.shape[0]
    if n > max_sample:
        idx = np.random.default_rng(seed).choice(n, max_sample, replace=False)
        Xs = X[idx]
    else:
        Xs = X
    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    edges = np.quantile(np.asarray(Xs, dtype=np.float64), qs, axis=0)
    return np.ascontiguousarray(edges.T.astype(np.float32))  # (d, nb-1)


@functools.partial(jax.jit, static_argnames=("d_pad",))
def binize(X: jax.Array, edges: jax.Array, *, d_pad: int) -> jax.Array:
    """Quantize rows to bins: (n, d) x (d, nb-1) -> (n, d_pad) uint8.

    bin = #{edges <= x}, computed as a broadcast compare-count in feature
    chunks — the searchsorted formulation lowers to a per-element binary
    search (~n*d*log(nb) serialized gathers, seconds at 131k x 256) while
    the compare-count is a fused VPU reduction (n*d*nb compare-adds,
    ~ms). Elementwise along rows, so XLA keeps the dp row sharding.
    Padding features (d..d_pad) get bin 0 and are masked out of split
    search.

    Input contract — FINITE values only. NaN compares false against every
    edge, so a NaN lands in bin 0 (the leftmost child everywhere below),
    where numpy's searchsorted would route it PAST the last edge into the
    rightmost bin. This routing is intentional and fixed (fit and
    transform quantize through this same function, so training and
    serving agree), but it is a semantics choice, not an accident — the
    estimator boundary enforces/documents the finite-input contract
    (``models/tree.py``, ``TPUML_RF_CHECK_FINITE``) rather than paying a
    per-element isnan pass here on the hot path.
    """
    n, d = X.shape
    Fc = max(1, min(d, (1 << 22) // max(n, 1)))  # bound the (n,Fc,nb) tile
    parts = []
    for c0 in range(0, d, Fc):
        xc = X[:, c0 : c0 + Fc]                       # (n, fc)
        ec = edges[c0 : c0 + Fc]                      # (fc, nb-1)
        cnt = (xc[:, :, None] >= ec[None, :, :]).sum(
            axis=2, dtype=jnp.int32
        )
        parts.append(cnt.astype(jnp.uint8))
    bins = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
    if d_pad > d:
        bins = jnp.pad(bins, ((0, 0), (0, d_pad - d)))
    return bins


# ---------------------------------------------------------------------------
# impurity
# ---------------------------------------------------------------------------


def _count(stats: jax.Array, impurity: str) -> jax.Array:
    """Row weight in a stats vector: class-count sum, or the weight slot."""
    if impurity == "variance":
        return stats[..., 0]
    return stats.sum(axis=-1)


def _impurity(stats: jax.Array, impurity: str) -> jax.Array:
    n = _count(stats, impurity)
    safe = jnp.maximum(n, 1e-12)
    if impurity == "variance":
        mean = stats[..., 1] / safe
        return jnp.maximum(stats[..., 2] / safe - mean * mean, 0.0)
    p = stats / safe[..., None]
    if impurity == "gini":
        return 1.0 - (p * p).sum(axis=-1)
    if impurity == "entropy":
        return -(jnp.where(p > 0.0, p * jnp.log2(jnp.maximum(p, 1e-30)), 0.0)).sum(
            axis=-1
        )
    raise ValueError(f"unknown impurity {impurity!r}")


def _chunk_features(
    d_pad: int, n_nodes: int, n_bins: int, n_stats: int, budget: int = _HIST_BUDGET
) -> int:
    """Largest power-of-two feature-chunk keeping the histogram tile in
    budget; d_pad is a power of two, so the chunk always divides it."""
    per_feat = max(1, n_nodes * n_bins * n_stats)
    f = max(1, budget // per_feat)
    f = 1 << (f.bit_length() - 1)
    return min(f, d_pad)


# ---------------------------------------------------------------------------
# contraction gather (TPU): per-row feature-subset bin extraction
# ---------------------------------------------------------------------------


def _pack_bins(bins: jax.Array) -> jax.Array:
    """(n, d) uint8 bins -> (n, d/4) int32, 4 bins per word (d % 4 == 0)."""
    b32 = bins.astype(jnp.int32)
    return (
        b32[:, 0::4]
        | (b32[:, 1::4] << 8)
        | (b32[:, 2::4] << 16)
        | (b32[:, 3::4] << 24)
    )


def _contract_gather(packed: jax.Array, idx: jax.Array) -> jax.Array:
    """bins[r, idx[r, j]] as a dense compare-select-reduce: (n, k) int32.

    TPU gathers run at ~1e8 elem/s, making ``take_along_axis`` of the
    per-node sampled columns the single dominant cost of an RF level
    (measured 25.5 ms of a ~33 ms level at 131k x 256, k=16). Expressed as
    a word-packed one-hot contraction the same extraction streams on the
    VPU at ~1.6 ms: compare idx>>2 against the d/4 word lanes, reduce, and
    shift the byte out. Feature-count sentinels yield bin 0 (see the
    sentinel invariant note below this function), and the gain search
    masks those slots exactly like the old clipped-gather path."""
    words = packed.shape[1]
    ar_w = jnp.arange(words, dtype=jnp.int32)
    sel = (idx[:, :, None] >> 2) == ar_w[None, None, :]
    w = jnp.where(sel, packed[:, None, :], 0).sum(-1)  # (n, k)
    return (w >> ((idx & 3) * 8)) & 0xFF


# Sentinel invariant for _contract_gather: a feature-count sentinel
# (idx == n_features) either matches NO word (n_features == d_pad) and
# yields 0, or lands in a zero-filled padding column (n_features < d_pad;
# binize pads bins with 0) and yields bin 0 — the same value the old
# clipped take_along_axis produced. Both cases rely on binize's zero fill
# of columns >= n_features, and the gain search additionally masks every
# sentinel slot via realf < n_features.


# ---------------------------------------------------------------------------
# compact histogram strategy (TPU): node-contiguous Pallas sub-blocks
# ---------------------------------------------------------------------------


def _compact_r_sub(n: int, n_nodes: int, R: int, S: int) -> int:
    """Per-level sub-block size: ~half the average node width, so the
    alignment padding stays ~+50% worst-case while sub-block count (and
    with it the final segment reduce) stays small at shallow levels.
    Capped so the kernel's (L*S, W) output block keeps a sublane dim
    that is a multiple of 8 (L = R // r_sub; Mosaic block rule)."""
    import math

    r = min(512, max(8, next_pow2(max(1, n // (n_nodes * 2)))))
    # (L*S) % 8 == 0 needs L a multiple of 8/gcd(S, 8); the fused-
    # selection kernel additionally needs L >= 8 for its feature-id
    # block, so cap at R/8 (costs a few extra sub-blocks per level at
    # shallow depths — sub-ms in the segment reduce)
    cap = min(R // (8 // math.gcd(S, 8)), R // 8)
    return max(1, min(r, cap, R))


def _sorted_block_reduce(partials2d, pstart, r_sub, n_nodes):
    """Per-node reduction of node-sorted sub-block partials via cumulative
    sums + boundary differences instead of a segment_sum scatter: the
    sub-blocks are already contiguous per node, so node g's histogram is
    ``C[pstart[g+1]/r_sub] - C[pstart[g]/r_sub]`` with C the zero-prefixed
    cumsum. Wide-row segment_sum measures ~3e6 rows/s; the cumsum runs at
    bandwidth and the boundary gather touches only n_nodes+1 rows.

    EXACT for integer stats while the GLOBAL per-column prefix stays
    < 2^24 (every f32 running sum is then an exactly-representable
    integer — note this bounds the whole column's cumsum, a stronger
    requirement than per-node sums, so callers gate on total row count);
    callers keep the scatter path for variance stats where cumsum
    reassociation would round, and for row counts where a concentrated
    bin could push a column prefix past 2^24."""
    C = jnp.concatenate(
        [jnp.zeros((1, partials2d.shape[1]), partials2d.dtype),
         jnp.cumsum(partials2d, axis=0)]
    )
    bounds = C[pstart[: n_nodes + 1] // r_sub]
    return bounds[1:] - bounds[:-1]


def _hist_compact(
    hist_src,             # (n, F) int bin values, or None with full_bins
    seg: jax.Array,       # (n,) int32 level-local node id; n_nodes = dead
    sw: jax.Array,        # (n, S) f32 stats*weight
    *,
    n_nodes: int,
    nb: int,
    r_sub: int,
    n_pad: int,           # from the caller's eligibility gate: the SAME
                          # block-aligned padded row count it validated
    f_chunk: int,         # feature-chunk width (gate-validated, divides F)
    variance: bool,
    full_bins=None,       # (n, d_pad) uint8 + feats => fused-selection
    feats=None,           # (n_nodes, F) int32 per-node feature ids
    interpret=None,
):
    """(F, n_nodes, nb, S) histogram + (n_nodes, S) parent stats via the
    node-contiguous Pallas path (``ops/rf_pallas.py``).

    One stable sort groups rows by node; every node's run is padded to an
    ``r_sub`` multiple so each aligned sub-block is node-pure; the Pallas
    kernel turns each sub-block into a (S, F*nb) histogram with a bin-only
    one-hot (NO node dimension — the whole point); and one wide-row
    segment-sum over the node-sorted sub-blocks finishes the per-node
    histograms. Parent stats fall out of the histogram (bin-sum of the
    first subset slot — slot 0 is always a real feature), saving the
    per-level parent scatter the other strategies pay.

    Measured v5e at 131k x 16 x 128 x 2 (level 12): ~41 ms for the
    scatter strategy's histogram vs ~1 ms kernel + ~4 ms glue here
    (scripts/rf_deep_microbench*.py).
    """
    from .rf_pallas import subblock_hist, subblock_hist_sel

    if full_bins is not None:
        n = full_bins.shape[0]
        F = feats.shape[1]
    else:
        n, F = hist_src.shape
    S = sw.shape[1]
    W = F * nb
    n_sb = n_pad // r_sub

    # stable sort of row ids by node: perm[j] = original row at sorted pos j
    iota = jnp.arange(n, dtype=jnp.int32)
    keys_s, perm = lax.sort((seg, iota), num_keys=1)
    # per-node source runs and r_sub-aligned destination runs
    starts = jnp.searchsorted(
        keys_s, jnp.arange(n_nodes + 1, dtype=jnp.int32), side="left"
    ).astype(jnp.int32)                                     # (n_nodes+1,)
    lens = starts[1:] - starts[:-1]                         # (n_nodes,)
    plen = -(-lens // r_sub) * r_sub
    pstart = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(plen)]
    )                                                       # (n_nodes+1,)
    # node of each sub-block (sub-blocks are node-pure by construction;
    # positions past the data resolve to the n_nodes dump slot)
    sb_pos = jnp.arange(n_sb, dtype=jnp.int32) * r_sub
    seg_sb = jnp.searchsorted(pstart[1:], sb_pos, side="right").astype(
        jnp.int32
    )                                                       # (n_sb,)
    # per-row source index: ONE small-table row gather at sub-block
    # granularity (n_sb rows), broadcast to rows — per-row gathers from
    # the (n_nodes,) tables would cost ~1 ms each at the elementwise
    # gather wall
    sbc = jnp.clip(seg_sb, 0, n_nodes - 1)
    tbl = jnp.stack([starts[:-1], pstart[:-1], lens], axis=1)
    tbl_rows = jnp.broadcast_to(
        tbl[sbc][:, None, :], (n_sb, r_sub, 3)
    ).reshape(n_pad, 3)
    pos = jnp.arange(n_pad, dtype=jnp.int32)
    off = pos - tbl_rows[:, 1]
    src = tbl_rows[:, 0] + off
    pvalid = (off < tbl_rows[:, 2]) & (
        jnp.broadcast_to(seg_sb[:, None], (n_sb, r_sub)).reshape(n_pad)
        < n_nodes
    )
    src2 = perm[jnp.clip(src, 0, n - 1)]
    swq = sw[src2] * pvalid[:, None].astype(sw.dtype)       # (n_pad, S)
    seg_red = jnp.where(seg_sb < n_nodes, seg_sb, n_nodes)

    # cumsum boundary-diff reduction only where EXACT (see
    # _sorted_block_reduce): integer stats AND total weighted rows small
    # enough that no per-column global prefix can reach 2^24 (Poisson
    # bootstrap weights average 1, so n rows bounds the count column up
    # to tail factors the 2^23 margin absorbs). Width-gated too: the
    # prefix array is a materialized (n_sb, W) transient, and at the
    # 1M x 3000 reference shape (W = 16384) the cumsum formulation
    # measured ~25% SLOWER end-to-end than the segment_sum it replaces
    # (182 s vs 146 s full fit) — keep it to bench-class widths
    def _use_cumsum(width):
        return (not variance) and n <= (1 << 23) and width <= 8192

    if full_bins is not None:
        # fused-selection path: ONE whole-row gather of the uint8 bins
        # (~93 GB/s — wide contiguous rows) + per-sub-block feature ids;
        # the kernel selects each node's k columns with an MXU one-hot
        # dot, replacing the per-row k-column gather that costs ~780 ms
        # per level at the reference 1M x 3000 shape. Dump sub-blocks
        # get garbage feature rows but zero weights — they contribute
        # nothing and reduce into the dropped slot.
        bq = full_bins[src2]                                # (n_pad, d_pad)
        featsq = feats[sbc]                                 # (n_sb, F)
        partials = subblock_hist_sel(
            bq, featsq, swq.T, n_bins=nb, r_sub=r_sub,
            variance=variance, interpret=interpret,
        )                                                   # (n_sb, S, F*nb)
        p2d = partials.reshape(n_sb, S * F * nb)
        if _use_cumsum(S * F * nb):
            hist_nodes = _sorted_block_reduce(
                p2d, pstart, r_sub, n_nodes
            ).reshape(n_nodes, S, F, nb)
        else:
            hist_nodes = jax.ops.segment_sum(
                p2d, seg_red, num_segments=n_nodes + 1
            )[:n_nodes].reshape(n_nodes, S, F, nb)
    else:
        # int32 bins always (hist_src may arrive uint8 from
        # take_along_axis): the kernel — and its lowering probe — see
        # exactly one input dtype
        binq = hist_src[src2].astype(jnp.int32)             # (n_pad, F)

        # feature-chunked kernel+reduce: the (n_sb, S, Fc*nb) partials
        # are the big transient (1.3 GB at the 1M x 3000 reference shape
        # in one shot) — bound them to ~256 MB; the gathers above happen
        # ONCE and chunks just slice binq
        Fc = f_chunk
        hist_parts = []
        for c0 in range(0, F, Fc):
            partials = subblock_hist(
                binq[:, c0 : c0 + Fc], swq, n_bins=nb, r_sub=r_sub,
                variance=variance, interpret=interpret,
            )                                               # (n_sb, S, Fc*nb)
            p2d = partials.reshape(n_sb, S * Fc * nb)
            if _use_cumsum(S * Fc * nb):
                part = _sorted_block_reduce(p2d, pstart, r_sub, n_nodes)
            else:
                part = jax.ops.segment_sum(
                    p2d, seg_red, num_segments=n_nodes + 1
                )[:n_nodes]
            hist_parts.append(part.reshape(n_nodes, S, Fc, nb))
        hist_nodes = (
            hist_parts[0]
            if len(hist_parts) == 1
            else jnp.concatenate(hist_parts, axis=2)
        )                                                   # (n_nodes, S, F, nb)
    parent = hist_nodes[:, :, 0, :].sum(axis=-1)            # (n_nodes, S)
    hist = hist_nodes.transpose(2, 0, 3, 1)                 # (F, n_nodes, nb, S)
    return hist, parent


def _best_splits_from_hist(hist, parent, pcount, pimp, realf, nb, cfg):
    """Best (gain, feature, bin) per node from a histogram block.

    ``hist`` is (F, n_nodes, nb, S); ``realf`` (F, n_nodes) maps block
    slots to real feature ids (sentinel = cfg.n_features, masked out).
    Shared by the chunked matmul/scatter strategies and the compact path.
    """
    cum = jnp.cumsum(hist, axis=2)
    left = cum[:, :, :-1, :]                 # threshold = bin b goes left
    right = parent[None, :, None, :] - left
    nl = _count(left, cfg.impurity)
    nr = _count(right, cfg.impurity)
    il = _impurity(left, cfg.impurity)
    ir = _impurity(right, cfg.impurity)
    denom = jnp.maximum(pcount, 1e-12)[None, :, None]
    gain = pimp[None, :, None] - (nl * il + nr * ir) / denom
    ok = (nl >= cfg.min_samples_leaf) & (nr >= cfg.min_samples_leaf)
    ok = ok & (realf < cfg.n_features)[:, :, None]
    gain = jnp.where(ok, gain, -jnp.inf)
    # per-(feature, node) best bin with CENTERED tie-breaking: equal
    # gains form a run across the empty-bin gap between the two row
    # populations; picking the middle edge approximates the midpoint
    # threshold exact tree builders use (robust for unseen rows near
    # the gap, where the first tied edge would hug the left side)
    m = gain.max(axis=2)                                # (F, n_nodes)
    tie = gain == m[:, :, None]
    first = jnp.argmax(tie, axis=2)
    last = (nb - 2) - jnp.argmax(tie[:, :, ::-1], axis=2)
    mid = (first + last + 1) // 2
    midg = jnp.take_along_axis(gain, mid[:, :, None], axis=2)[:, :, 0]
    bbin = jnp.where(midg == m, mid, first)             # (F, n_nodes)
    fi = jnp.argmax(m, axis=0)                          # (n_nodes,)
    g = jnp.take_along_axis(m, fi[None, :], axis=0)[0]
    f = jnp.take_along_axis(realf, fi[None, :], axis=0)[0]
    b = jnp.take_along_axis(bbin, fi[None, :], axis=0)[0].astype(jnp.int32)
    return g, f, b


# ---------------------------------------------------------------------------
# single-tree level-wise builder
# ---------------------------------------------------------------------------


def _build_tree(
    bins: jax.Array,    # (n, d_pad) uint8
    stats: jax.Array,   # (n, S) float
    valid: jax.Array,   # (n,) float row mask
    key: jax.Array,
    cfg: ForestConfig,
) -> Dict[str, jax.Array]:
    n, d_pad = bins.shape
    S = cfg.n_stats
    nb = cfg.n_bins
    M = max_nodes(cfg.max_depth)
    dt = stats.dtype

    kb, kf = jax.random.split(jnp.asarray(key))
    if cfg.bootstrap:
        # Poisson(1) bootstrap ~ sampling-with-replacement. Draws are
        # indexed by LOGICAL row position (cumsum of the validity mask),
        # not padded position: multi-process layouts interleave padding
        # per-process block, and logical indexing makes the same dataset
        # produce the same weights — and therefore bit-identical
        # integer-stat trees — under any process/padding layout.
        logical = jnp.clip(
            jnp.cumsum(valid.astype(jnp.int32)) - 1, 0, n - 1
        )
        draws = jax.random.poisson(kb, 1.0, (n,)).astype(dt)
        w = draws[logical] * valid
    else:
        w = valid.astype(dt)
    sw = stats * w[:, None]

    feat = jnp.full((M,), -1, jnp.int32)
    thr_bin = jnp.zeros((M,), jnp.int32)
    leaf = jnp.zeros((M, S), dt)
    gains = jnp.zeros((M,), dt)
    node = jnp.zeros((n,), jnp.int32)

    # Word-packed bins for the contraction gather (TPU: per-row gathers run
    # at ~1e8 elem/s, making take_along_axis ~16x slower than the dense
    # formulation at d_pad=256; CPU keeps take_along_axis). The contraction
    # does d_pad/4 word-ops per extracted element (~8.6e10 word-ops/s
    # measured), so its advantage erodes linearly with width — "auto" caps
    # it at d_pad<=1024 (4x the measured shape), past which the predicted
    # win thins and the un-fused intermediate risk grows. Packed once per
    # tree, outside the level loop.
    if cfg.contract_gather == "on":
        use_contract = d_pad % 4 == 0
    elif cfg.contract_gather == "off":
        use_contract = False
    else:
        use_contract = (
            jax.default_backend() == "tpu"
            and d_pad % 4 == 0
            and d_pad <= 1024
        )
    packed = _pack_bins(bins) if use_contract else None

    # levels are a static python loop: each level has its own (static) node
    # count and feature-chunk size, so XLA compiles tight fixed-shape kernels
    for level in range(cfg.max_depth + 1):
        offset = (1 << level) - 1
        n_nodes = 1 << level
        local = node - offset
        in_level = (local >= 0) & (local < n_nodes)
        seg = jnp.where(in_level, local, n_nodes).astype(jnp.int32)
        if level == cfg.max_depth:
            # final level: leaf stats only — the one remaining per-level
            # parent scatter (the compact path below derives parent from
            # its histogram on every split level)
            parent = jax.ops.segment_sum(sw, seg, num_segments=n_nodes + 1)[
                :n_nodes
            ]
            leaf = leaf.at[offset : offset + n_nodes].set(parent)
            break

        # Per-node feature subsampling (cuML max_features semantics): the
        # k_features highest of a per-(node, feature) uniform draw. The
        # subset is EXPLOITED, not just masked: each row gathers its
        # node's k selected feature bins and the histogram covers only
        # those k virtual features — n*k*S updates per level instead of
        # n*d*S. At the reference's own semantics (featureSubsetStrategy
        # "auto" -> sqrt(d) for classification) that is a 16x cut at
        # d=256 and ~55x at the 1M x 3000 benchmark shape, which is what
        # makes the reference forest config fit a single-chip build.
        subset = cfg.k_features < cfg.n_features
        if subset:
            r = jax.random.uniform(
                jax.random.fold_in(kf, level), (n_nodes, cfg.n_features)
            )
            if jax.default_backend() == "tpu":
                # indices of the k largest uniforms are a uniform random
                # k-subset either way; PartialReduce at recall 1.0 is exact
                # and ~4x cheaper than full-sort top_k at (4096, 256)
                feats = lax.approx_max_k(
                    r, cfg.k_features, recall_target=1.0
                )[1].astype(jnp.int32)
            else:
                feats = lax.top_k(r, cfg.k_features)[1].astype(jnp.int32)
            k_pad = next_pow2(cfg.k_features)
            if k_pad > cfg.k_features:
                # sentinel n_features: invalid (masked out of gain search)
                feats = jnp.pad(
                    feats,
                    ((0, 0), (0, k_pad - cfg.k_features)),
                    constant_values=cfg.n_features,
                )
            d_hist = k_pad
        else:
            feats = None
            d_hist = d_pad

        def make_hist_src(feats=feats, local=local):
            """Per-row subset bin extraction — only materialized by the
            strategies that need it (the fused-selection kernel selects
            in-kernel and skips this entirely)."""
            if not subset:
                return bins
            lc0 = jnp.clip(local, 0, n_nodes - 1)
            row_feats = feats[lc0]  # (n, k_pad) real feature ids per row
            if use_contract:
                return _contract_gather(packed, row_feats)  # (n, k_pad) i32
            return jnp.take_along_axis(
                bins, jnp.clip(row_feats, 0, d_pad - 1), axis=1
            )  # (n, k_pad) uint8

        # compact strategy (TPU): node-contiguous rows + the Pallas
        # sub-block kernel (ops/rf_pallas.py). Eligibility is static per
        # level: f32 stats, lane-aligned one-hot width, a full-level
        # histogram tile that fits HBM comfortably, and a probed
        # lowering. Wins by ~8x per level over the scatter wall at the
        # bench shape (scripts/rf_deep_microbench2.py), on every level —
        # scatter cost is n-bound, so shallow levels paid it too.
        from .rf_pallas import BLOCK_ROWS, rf_hist_pallas_ok, rf_hist_sel_ok

        r_sub = _compact_r_sub(n, n_nodes, BLOCK_ROWS, S)
        # Pad with the DEEPEST split level's node count when that waste
        # is small relative to n: r_sub converges to its cap at scale,
        # so one padded row count then serves every level and the Pallas
        # kernels compile ONCE per tree config instead of once per level
        # (measured ~107 s Mosaic compile for the fused-selection kernel
        # at the 1M x 3072 shape — 13 per-level compiles would cost
        # ~20 min). At small n the uniform pad would triple the kernel's
        # row count (observed: bench rf 4.5 s -> 10.4 s), so fall back
        # to per-level padding there — those shapes compile in seconds.
        n_nodes_max = 1 << max(0, cfg.max_depth - 1)
        if (n_nodes_max + 1) * r_sub * 3 <= n:
            n_pad_c = (
                -(-(n + (n_nodes_max + 1) * r_sub) // BLOCK_ROWS)
                * BLOCK_ROWS
            )
        else:
            n_pad_c = (
                -(-(n + (n_nodes + 1) * r_sub) // BLOCK_ROWS) * BLOCK_ROWS
            )
        n_sb_c = n_pad_c // r_sub
        # feature chunk: largest power of two satisfying the kernel's
        # one-hot width cap (Fc*nb <= 8192) AND a ~256 MB partials
        # transient budget (single-shot partials OOMed the 1M x 3000
        # reference shape alongside its other residents); must divide
        # d_hist
        Fc = 1 << max(0, min(d_hist, 8192 // nb).bit_length() - 1)
        while Fc > 1 and (
            d_hist % Fc != 0 or n_sb_c * S * Fc * nb * 4 > (256 << 20)
        ):
            Fc //= 2
        compact_shape_ok = (
            cfg.hist_strategy in ("auto", "compact")
            and dt == jnp.float32
            and d_hist % Fc == 0
            and n_nodes * d_hist * nb * S <= (1 << 28)
        )
        # fused-selection variant: in-kernel per-node column selection
        # over node-sorted FULL bins rows — skips the per-row subset
        # gather entirely (the single dominant cost at wide d: ~780 ms
        # per level at 1M x 3000). Single-shot (no feature chunking), so
        # its transients are gated against an HBM budget instead: the
        # probe compiles a tiny instance and cannot see HBM pressure,
        # and a runtime OOM here has no fallback. Residents counted:
        # bins + the row-gathered copy (both n-scale uint8), partials,
        # and two histogram tiles; the sort/index arrays are a few
        # percent of these and deliberately ignored.
        sel_resident = (
            n * d_pad                      # bins (uint8)
            + n_pad_c * d_pad              # gathered node-sorted copy
            + n_sb_c * S * d_hist * nb * 4  # partials (f32)
            + 2 * n_nodes * S * d_hist * nb * 4  # hist + transpose
        )
        sel_budget = _sel_hbm_budget()
        use_sel = (
            compact_shape_ok
            and subset
            # only where the per-row subset gather is the dominant cost
            # (see _SEL_MIN_DPAD; at bench d_pad=256 fused engagement
            # SLOWED rf 4.5 -> 10.4 s)
            and d_pad > _SEL_MIN_DPAD
            and sel_resident <= sel_budget
            and rf_hist_sel_ok(
                n_pad_c, d_pad, d_hist, nb, S, r_sub,
                variance=(cfg.impurity == "variance"),
            )
        )
        use_compact = use_sel or (
            compact_shape_ok
            and rf_hist_pallas_ok(
                n_pad_c, Fc, nb, S, r_sub,
                variance=(cfg.impurity == "variance"),
            )
        )
        if use_sel:
            hist_full, parent = _hist_compact(
                None, seg, sw, n_nodes=n_nodes, nb=nb, r_sub=r_sub,
                n_pad=n_pad_c, f_chunk=Fc,
                variance=(cfg.impurity == "variance"),
                full_bins=bins, feats=feats,
            )
        elif use_compact:
            hist_full, parent = _hist_compact(
                make_hist_src(), seg, sw, n_nodes=n_nodes, nb=nb,
                r_sub=r_sub, n_pad=n_pad_c, f_chunk=Fc,
                variance=(cfg.impurity == "variance"),
            )
        else:
            parent = jax.ops.segment_sum(sw, seg, num_segments=n_nodes + 1)[
                :n_nodes
            ]
        leaf = leaf.at[offset : offset + n_nodes].set(parent)
        pcount = _count(parent, cfg.impurity)
        pimp = _impurity(parent, cfg.impurity)

        if use_compact:
            if subset:
                realf_full = feats.T  # (k_pad, n_nodes) real feature ids
            else:
                realf_full = jnp.broadcast_to(
                    jnp.arange(d_hist, dtype=jnp.int32)[:, None],
                    (d_hist, n_nodes),
                )
            # gain search in feature-slot chunks: holding the full
            # (F, n_nodes, nb, S) histogram once is fine, but the
            # cumsum/left/right/gain chain materializes several copies of
            # the tile — ~1.5 GB of transients at the reference shape on
            # a tunnel chip with ~8 GB visible HBM. Chunk merging uses
            # the same init and strict-> update as the chunk-scan path,
            # so results (including the (0, 0) feature/bin of no-gain
            # nodes and first-slot tie-breaking) stay bit-identical.
            Fc = d_hist
            while Fc > 1 and Fc * n_nodes * nb * S > 4 * _HIST_BUDGET:
                Fc //= 2
            bg = jnp.full((n_nodes,), -jnp.inf, dt)
            bf = jnp.zeros((n_nodes,), jnp.int32)
            bb = jnp.zeros((n_nodes,), jnp.int32)
            for c0 in range(0, d_hist, Fc):
                g, f, b = _best_splits_from_hist(
                    hist_full[c0 : c0 + Fc], parent, pcount, pimp,
                    realf_full[c0 : c0 + Fc], nb, cfg,
                )
                upd = g > bg
                bg = jnp.where(upd, g, bg)
                bf = jnp.where(upd, f, bf)
                bb = jnp.where(upd, b, bb)
        else:
            # strategy per level (static). Subset path: the gathered operand is
            # only k_pad wide, and measured v5e scatter on it is ~2.2 ms/level
            # FLAT in n_nodes while the one-hot matmul grows past 8 ms — scatter
            # always wins. No-subset path: one-hot matmuls on the MXU until the
            # 2*n_nodes*nb waste factor exceeds a scatter-add update's cost.
            # "auto" is TPU-only: the trade inverts on CPU, where scatter-adds
            # are cheap and dense one-hot matmuls are pure waste (a CPU run of
            # the reference forest config went from ~seconds to minutes).
            if cfg.hist_strategy == "matmul":
                use_matmul = True
            elif cfg.hist_strategy in ("scatter", "compact"):
                # forced-compact levels that fail the eligibility gate
                # take scatter, as resolve_hist_strategy documents —
                # matmul would silently change variance-stat numerics
                use_matmul = False
            elif subset:
                use_matmul = False
            else:
                use_matmul = (
                    jax.default_backend() == "tpu"
                    and (2.0 * n_nodes * nb) < _SCATTER_EQ_FLOPS
                )

            # the narrow subset-scatter tile ((k_pad, n_nodes*nb, S): 67 MB at
            # k=16/depth-13) runs single-chunk under a raised budget — chunking
            # it only multiplied fixed scatter overheads
            hist_src = make_hist_src()
            budget = (1 << 25) if (subset and not use_matmul) else _HIST_BUDGET
            F = _chunk_features(d_hist, n_nodes, nb, S, budget)
            n_chunks = d_hist // F
            if use_matmul:
                # the (C, F*nb) bin one-hot is a materialized dot operand; the
                # histogram-tile budget alone lets F reach d_pad at shallow
                # levels (17 GB at d_pad=4096, C=8192, nb=128) — cap F so the
                # one-hot stays ~256 MB. Extra feature chunks cost nothing:
                # total matmul flops per level are F-invariant.
                C_lvl = min(_ROW_CHUNK, n)
                f_cap = max(1, (1 << 26) // (C_lvl * nb))
                f_cap = 1 << (f_cap.bit_length() - 1)
                F = min(F, f_cap)
                n_chunks = d_hist // F

            def _hist_scatter(binc, *, n_nodes, in_level, local, sw):
                """(F, n_nodes, nb, S) via segment_sum scatter-adds."""
                ids = jnp.where(
                    in_level[:, None], local[:, None] * nb + binc, n_nodes * nb
                )
                # Small S (regression stats, binary/few-class): one scalar
                # segment_sum per stat column — vmapping the (n, S) operand
                # broadcasts it to (F, n, S) with the tiny S minor dim
                # lane-padded S -> 128 on TPU, a 64x memory expansion at S=2
                # (16 GB observed at n=131k, F=256); per-stat 1-D operands
                # keep the broadcast at (F, n), lane-aligned. Wide S (many
                # classes): padding overhead fades (<= 8x at S >= 16) and S
                # unrolled scatters would dominate — keep one (n, S) scatter.
                F = binc.shape[1]
                if S <= 16:
                    hist = jnp.stack(
                        [
                            jax.vmap(
                                lambda col, c=sw[:, s]: jax.ops.segment_sum(
                                    c, col, num_segments=n_nodes * nb + 1
                                ),
                                in_axes=1,
                            )(ids)                       # (F, n_nodes*nb+1)
                            for s in range(S)
                        ],
                        axis=-1,
                    )                                    # (F, n_nodes*nb+1, S)
                else:
                    hist = jax.vmap(
                        lambda col: jax.ops.segment_sum(
                            sw, col, num_segments=n_nodes * nb + 1
                        ),
                        in_axes=1,
                    )(ids)                               # (F, n_nodes*nb+1, S)
                return hist[:, : n_nodes * nb, :].reshape(F, n_nodes, nb, S)

            def _hist_matmul(binc, *, n_nodes, in_level, local, sw):
                """(F, n_nodes, nb, S) via MXU one-hot contractions.

                hist[f,nd,b,s] = sum_r N[r,nd] * B[r,f*nb+b] * sw[r,s] with
                N the (row, node) one-hot (row weight/level mask folded in) and
                B the (row, feature-bin) one-hot — one (n_nodes, C) x (C, F*nb)
                matmul per stat per row chunk. Rows are accumulated in chunks
                so the one-hot intermediates stay bounded; the clamped last
                chunk masks re-read rows."""
                F = binc.shape[1]
                C = min(_ROW_CHUNK, n)
                nc = -(-n // C)
                node_ar = jnp.arange(n_nodes, dtype=jnp.int32)
                bin_ar = jnp.arange(nb, dtype=jnp.int32)

                def row_body(ri, acc):
                    start = jnp.minimum(ri * C, n - C)
                    bc = lax.dynamic_slice(binc, (start, 0), (C, F))
                    loc = lax.dynamic_slice(local, (start,), (C,))
                    lvl = lax.dynamic_slice(in_level, (start,), (C,))
                    swc = lax.dynamic_slice(sw, (start, 0), (C, S))
                    fresh = (start + jnp.arange(C)) >= ri * C  # clamp re-reads
                    Noh = (
                        (loc[:, None] == node_ar[None, :])
                        & lvl[:, None]
                        & fresh[:, None]
                    ).astype(dt)                              # (C, n_nodes)
                    Boh = (bc[:, :, None] == bin_ar[None, None, :]).astype(dt)
                    Boh = Boh.reshape(C, F * nb)              # (C, F*nb)
                    # TPU's default f32 matmul uses bf16 multiplies — exact for
                    # classification (one-hots and small-integer weights are
                    # bf16-representable; accumulation is f32) but NOT for
                    # variance stats carrying y/y^2, where rounding would flip
                    # near-tied splits vs the scatter path. Those pay the
                    # multi-pass HIGHEST f32 emulation.
                    prec = (
                        lax.Precision.HIGHEST
                        if cfg.impurity == "variance"
                        else None
                    )
                    return acc + jnp.stack(
                        [
                            jnp.matmul(
                                (Noh * swc[:, s][:, None]).T, Boh, precision=prec
                            )
                            for s in range(S)
                        ],
                        axis=-1,
                    )                                         # (n_nodes, F*nb, S)

                acc = lax.fori_loop(
                    0,
                    nc,
                    row_body,
                    jnp.zeros((n_nodes, F * nb, S), dt),
                )
                return acc.reshape(n_nodes, F, nb, S).transpose(1, 0, 2, 3)

            def chunk_body(carry, ci, *, n_nodes=n_nodes, parent=parent,
                           pcount=pcount, pimp=pimp, feats=feats, F=F,
                           in_level=in_level, local=local, sw=sw,
                           use_matmul=use_matmul, subset=subset,
                           hist_src=hist_src):
                bg, bf, bb = carry
                binc = lax.dynamic_slice(
                    hist_src, (0, ci * F), (n, F)
                ).astype(jnp.int32)
                make = _hist_matmul if use_matmul else _hist_scatter
                hist = make(
                    binc, n_nodes=n_nodes, in_level=in_level, local=local, sw=sw
                )
                if subset:
                    # real feature id per (virtual feature, node), this chunk
                    realf = lax.dynamic_slice(
                        feats, (0, ci * F), (n_nodes, F)
                    ).T                                      # (F, n_nodes)
                else:
                    realf = jnp.broadcast_to(
                        (ci * F + jnp.arange(F, dtype=jnp.int32))[:, None],
                        (F, n_nodes),
                    )
                g, f, b = _best_splits_from_hist(
                    hist, parent, pcount, pimp, realf, nb, cfg
                )
                upd = g > bg
                return (
                    jnp.where(upd, g, bg),
                    jnp.where(upd, f, bf),
                    jnp.where(upd, b, bb),
                ), None

            init = (
                jnp.full((n_nodes,), -jnp.inf, dt),
                jnp.zeros((n_nodes,), jnp.int32),
                jnp.zeros((n_nodes,), jnp.int32),
            )
            (bg, bf, bb), _ = lax.scan(chunk_body, init, jnp.arange(n_chunks))

        do_split = (
            jnp.isfinite(bg)
            & (bg >= max(cfg.min_info_gain, 1e-9))
            & (pcount >= cfg.min_samples_split)
        )
        feat = feat.at[offset : offset + n_nodes].set(jnp.where(do_split, bf, -1))
        thr_bin = thr_bin.at[offset : offset + n_nodes].set(bb)
        gains = gains.at[offset : offset + n_nodes].set(
            jnp.where(do_split, bg, jnp.zeros_like(bg))
        )

        # route rows to children; rows whose node became a leaf stay put
        lc = jnp.clip(local, 0, n_nodes - 1)
        row_feat = bf[lc]
        if use_contract:
            row_bin = _contract_gather(packed, row_feat[:, None])[:, 0]
        else:
            row_bin = jnp.take_along_axis(
                bins, jnp.clip(row_feat, 0, d_pad - 1)[:, None], axis=1
            )[:, 0].astype(jnp.int32)
        go_right = (row_bin > bb[lc]).astype(jnp.int32)
        child = 2 * node + 1 + go_right
        moves = in_level & do_split[lc]
        node = jnp.where(moves, child, node)

    return {"feature": feat, "threshold_bin": thr_bin, "leaf_stats": leaf, "gain": gains}


# ---------------------------------------------------------------------------
# tree-batched level-wise builder: T trees advance one level per dispatch
# ---------------------------------------------------------------------------


def _seg_sum_trees(vals, seg, num):
    """Per-tree segment sums fused into ONE global scatter.

    ``vals`` (T, n, ...) and ``seg`` (T, n) in [0, num) reduce to
    (T, num, ...) by offsetting tree t's segment ids by ``t * num`` —
    trees touch disjoint segment ranges and every tree's rows keep their
    original order, so each tree's accumulation sequence is exactly the
    per-tree ``segment_sum``'s (bitwise identical), while the device sees
    a single scatter over T*n rows instead of T small ones.
    """
    T, n = seg.shape
    gseg = seg + (num * jnp.arange(T, dtype=jnp.int32))[:, None]
    flat = vals.reshape((T * n,) + vals.shape[2:])
    out = jax.ops.segment_sum(flat, gseg.reshape(T * n), num_segments=T * num)
    return out.reshape((T, num) + vals.shape[2:])


def _hist_compact_batched(
    hist_src,             # (T, n, F) int bins, or None with full_bins
    seg: jax.Array,       # (T, n) int32 level-local node id; n_nodes = dead
    sw: jax.Array,        # (T, n, S) f32 stats*weight
    *,
    n_nodes: int,
    nb: int,
    r_sub: int,
    n_pad: int,
    f_chunk: int,
    variance: bool,
    full_bins=None,       # (n, d_pad) uint8 SHARED rows + feats => fused-sel
    feats=None,           # (T, n_nodes, F) int32 per-node feature ids
    interpret=None,
):
    """T-batched ``_hist_compact``: (T, F, n_nodes, nb, S) + (T, n_nodes, S).

    The per-tree sort/searchsorted bookkeeping is vmapped (cheap index
    math), but the Pallas kernel runs ONCE over the flattened
    (T*n_pad) rows: the kernel's grid blocks are ``BLOCK_ROWS``-aligned
    and ``n_pad % BLOCK_ROWS == 0`` (caller gate), so every block is
    tree-pure and the flattened call computes exactly the per-tree
    blocks back to back — bitwise identical to T separate calls.
    """
    from .rf_pallas import subblock_hist_batched, subblock_hist_sel_batched

    T = seg.shape[0]
    if full_bins is not None:
        n = full_bins.shape[0]
        F = feats.shape[-1]
    else:
        n, F = hist_src.shape[-2], hist_src.shape[-1]
    S = sw.shape[-1]
    n_sb = n_pad // r_sub
    iota = jnp.arange(n, dtype=jnp.int32)

    def prep(seg_t, sw_t):
        # mirror of _hist_compact's index math, one tree at a time
        keys_s, perm = lax.sort((seg_t, iota), num_keys=1)
        starts = jnp.searchsorted(
            keys_s, jnp.arange(n_nodes + 1, dtype=jnp.int32), side="left"
        ).astype(jnp.int32)
        lens = starts[1:] - starts[:-1]
        plen = -(-lens // r_sub) * r_sub
        pstart = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(plen)]
        )
        sb_pos = jnp.arange(n_sb, dtype=jnp.int32) * r_sub
        seg_sb = jnp.searchsorted(pstart[1:], sb_pos, side="right").astype(
            jnp.int32
        )
        sbc = jnp.clip(seg_sb, 0, n_nodes - 1)
        tbl = jnp.stack([starts[:-1], pstart[:-1], lens], axis=1)
        tbl_rows = jnp.broadcast_to(
            tbl[sbc][:, None, :], (n_sb, r_sub, 3)
        ).reshape(n_pad, 3)
        pos = jnp.arange(n_pad, dtype=jnp.int32)
        off = pos - tbl_rows[:, 1]
        src = tbl_rows[:, 0] + off
        pvalid = (off < tbl_rows[:, 2]) & (
            jnp.broadcast_to(seg_sb[:, None], (n_sb, r_sub)).reshape(n_pad)
            < n_nodes
        )
        src2 = perm[jnp.clip(src, 0, n - 1)]
        swq = sw_t[src2] * pvalid[:, None].astype(sw_t.dtype)
        seg_red = jnp.where(seg_sb < n_nodes, seg_sb, n_nodes)
        return src2, swq, seg_red, pstart, sbc

    src2, swq, seg_red, pstart, sbc = jax.vmap(prep)(seg, sw)

    def _use_cumsum(width):
        return (not variance) and n <= (1 << 23) and width <= 8192

    def reduce_partials(p2d, width):  # (T, n_sb, width) -> (T, n_nodes, width)
        if _use_cumsum(width):
            # vmapped cumsum + boundary diff: per-tree scan order unchanged
            return jax.vmap(
                lambda p, ps: _sorted_block_reduce(p, ps, r_sub, n_nodes)
            )(p2d, pstart)
        return _seg_sum_trees(p2d, seg_red, n_nodes + 1)[:, :n_nodes]

    if full_bins is not None:
        bq = jax.vmap(lambda s2: full_bins[s2])(src2)       # (T, n_pad, d_pad)
        featsq = jax.vmap(lambda f, c: f[c])(feats, sbc)    # (T, n_sb, F)
        partials = subblock_hist_sel_batched(
            bq, featsq, swq.transpose(0, 2, 1), n_bins=nb, r_sub=r_sub,
            variance=variance, interpret=interpret,
        )                                                   # (T, n_sb, S, F*nb)
        hist_nodes = reduce_partials(
            partials.reshape(T, n_sb, S * F * nb), S * F * nb
        ).reshape(T, n_nodes, S, F, nb)
    else:
        if hist_src.ndim == 2:      # shared full bins (no subset)
            binq = jax.vmap(lambda s2: hist_src[s2])(src2).astype(jnp.int32)
        else:                       # per-tree subset-gathered bins
            binq = jax.vmap(lambda h, s2: h[s2])(hist_src, src2).astype(
                jnp.int32
            )                                               # (T, n_pad, F)
        Fc = f_chunk
        hist_parts = []
        for c0 in range(0, F, Fc):
            partials = subblock_hist_batched(
                binq[:, :, c0 : c0 + Fc], swq, n_bins=nb, r_sub=r_sub,
                variance=variance, interpret=interpret,
            )                                               # (T, n_sb, S, Fc*nb)
            part = reduce_partials(
                partials.reshape(T, n_sb, S * Fc * nb), S * Fc * nb
            )
            hist_parts.append(part.reshape(T, n_nodes, S, Fc, nb))
        hist_nodes = (
            hist_parts[0]
            if len(hist_parts) == 1
            else jnp.concatenate(hist_parts, axis=3)
        )                                                   # (T, n_nodes, S, F, nb)
    parent = hist_nodes[:, :, :, 0, :].sum(axis=-1)         # (T, n_nodes, S)
    hist = hist_nodes.transpose(0, 3, 1, 4, 2)              # (T, F, n_nodes, nb, S)
    return hist, parent


def _grow_trees_batched(
    bins: jax.Array,    # (n, d_pad) uint8, shared across the tree batch
    sw: jax.Array,      # (T, n, S) float stats*weight per tree
    kf: jax.Array,      # (T, 2) per-tree feature-subset keys
    cfg: ForestConfig,
    *,
    axis_name=None,
    return_rows: bool = False,
) -> Dict[str, jax.Array]:
    """T-batched mirror of ``_build_tree``'s level loop.

    All T trees advance one level per dispatch: per-node histogram
    accumulations fuse into ONE (T*nodes)-segmented scatter / one
    tall-skinny (T*nodes, C) x (C, F*nb) one-hot matmul / one flattened
    Pallas sub-block kernel call, and the gain search vmaps over the tree
    axis. Every step either is a per-tree gather/elementwise op under
    vmap or preserves each tree's per-segment accumulation order (see
    _seg_sum_trees / _hist_compact_batched), and the per-level strategy
    gates are the SAME static expressions as the sequential builder —
    so fitted trees are bit-identical to ``_build_tree`` at the same
    keys (tests/test_tree_batch.py pins this per strategy).

    ``axis_name``: optional mesh axis to ``psum`` histograms and parent
    stats over — the data-parallel hook the GBT boosting loop uses to
    grow each round's trees on ALL rows while rows stay sharded. RF keeps
    it None (each tree trains on its device's shard by design).
    ``return_rows``: also return each row's final node id (T, n) —
    the boosting loop reads leaf assignments from it without a second
    descent.
    """
    n, d_pad = bins.shape
    T = sw.shape[0]
    S = cfg.n_stats
    nb = cfg.n_bins
    M = max_nodes(cfg.max_depth)
    dt = sw.dtype

    def _allred(x):
        return lax.psum(x, axis_name) if axis_name is not None else x

    feat = jnp.full((T, M), -1, jnp.int32)
    thr_bin = jnp.zeros((T, M), jnp.int32)
    leaf = jnp.zeros((T, M, S), dt)
    gains = jnp.zeros((T, M), dt)
    node = jnp.zeros((T, n), jnp.int32)

    if cfg.contract_gather == "on":
        use_contract = d_pad % 4 == 0
    elif cfg.contract_gather == "off":
        use_contract = False
    else:
        use_contract = (
            jax.default_backend() == "tpu"
            and d_pad % 4 == 0
            and d_pad <= 1024
        )
    packed = _pack_bins(bins) if use_contract else None

    for level in range(cfg.max_depth + 1):
        offset = (1 << level) - 1
        n_nodes = 1 << level
        local = node - offset                           # (T, n)
        in_level = (local >= 0) & (local < n_nodes)
        seg = jnp.where(in_level, local, n_nodes).astype(jnp.int32)
        if level == cfg.max_depth:
            parent = _allred(
                _seg_sum_trees(sw, seg, n_nodes + 1)[:, :n_nodes]
            )
            leaf = leaf.at[:, offset : offset + n_nodes].set(parent)
            break

        subset = cfg.k_features < cfg.n_features
        if subset:
            # per-tree draws via lax.map of the sequential builder's exact
            # call — identical uniforms per (tree, level) by construction;
            # top-k rows are independent, so the (T*n_nodes)-row batch
            # selects identical subsets
            r = lax.map(
                lambda k: jax.random.uniform(
                    jax.random.fold_in(k, level),
                    (n_nodes, cfg.n_features),
                ),
                kf,
            ).reshape(T * n_nodes, cfg.n_features)
            if jax.default_backend() == "tpu":
                feats = lax.approx_max_k(
                    r, cfg.k_features, recall_target=1.0
                )[1].astype(jnp.int32)
            else:
                feats = lax.top_k(r, cfg.k_features)[1].astype(jnp.int32)
            k_pad = next_pow2(cfg.k_features)
            if k_pad > cfg.k_features:
                feats = jnp.pad(
                    feats,
                    ((0, 0), (0, k_pad - cfg.k_features)),
                    constant_values=cfg.n_features,
                )
            feats = feats.reshape(T, n_nodes, k_pad)
            d_hist = k_pad
        else:
            feats = None
            d_hist = d_pad

        def make_hist_src(feats=feats, local=local):
            if not subset:
                return bins                             # (n, d_pad) shared
            lc0 = jnp.clip(local, 0, n_nodes - 1)       # (T, n)
            row_feats = jax.vmap(lambda f, l: f[l])(feats, lc0)
            if use_contract:
                return jax.vmap(
                    lambda rf_: _contract_gather(packed, rf_)
                )(row_feats)                            # (T, n, k_pad) i32
            return jax.vmap(
                lambda rf_: jnp.take_along_axis(
                    bins, jnp.clip(rf_, 0, d_pad - 1), axis=1
                )
            )(row_feats)                                # (T, n, k_pad) u8

        # compact-strategy eligibility: the SAME per-tree static
        # expressions as _build_tree — resolve_tree_batch's budget is
        # what accounts for the xT transients, NOT these gates, so both
        # builders always pick the same strategy per level
        from .rf_pallas import BLOCK_ROWS, rf_hist_pallas_ok, rf_hist_sel_ok

        r_sub = _compact_r_sub(n, n_nodes, BLOCK_ROWS, S)
        n_nodes_max = 1 << max(0, cfg.max_depth - 1)
        if (n_nodes_max + 1) * r_sub * 3 <= n:
            n_pad_c = (
                -(-(n + (n_nodes_max + 1) * r_sub) // BLOCK_ROWS)
                * BLOCK_ROWS
            )
        else:
            n_pad_c = (
                -(-(n + (n_nodes + 1) * r_sub) // BLOCK_ROWS) * BLOCK_ROWS
            )
        n_sb_c = n_pad_c // r_sub
        Fc = 1 << max(0, min(d_hist, 8192 // nb).bit_length() - 1)
        while Fc > 1 and (
            d_hist % Fc != 0 or n_sb_c * S * Fc * nb * 4 > (256 << 20)
        ):
            Fc //= 2
        compact_shape_ok = (
            cfg.hist_strategy in ("auto", "compact")
            and dt == jnp.float32
            and d_hist % Fc == 0
            and n_nodes * d_hist * nb * S <= (1 << 28)
        )
        sel_resident = (
            n * d_pad
            + n_pad_c * d_pad
            + n_sb_c * S * d_hist * nb * 4
            + 2 * n_nodes * S * d_hist * nb * 4
        )
        sel_budget = _sel_hbm_budget()
        use_sel = (
            compact_shape_ok
            and subset
            and d_pad > _SEL_MIN_DPAD
            and sel_resident <= sel_budget
            and rf_hist_sel_ok(
                n_pad_c, d_pad, d_hist, nb, S, r_sub,
                variance=(cfg.impurity == "variance"),
            )
        )
        use_compact = use_sel or (
            compact_shape_ok
            and rf_hist_pallas_ok(
                n_pad_c, Fc, nb, S, r_sub,
                variance=(cfg.impurity == "variance"),
            )
        )
        if use_sel:
            hist_full, parent = _hist_compact_batched(
                None, seg, sw, n_nodes=n_nodes, nb=nb, r_sub=r_sub,
                n_pad=n_pad_c, f_chunk=Fc,
                variance=(cfg.impurity == "variance"),
                full_bins=bins, feats=feats,
            )
        elif use_compact:
            hist_full, parent = _hist_compact_batched(
                make_hist_src(), seg, sw, n_nodes=n_nodes, nb=nb,
                r_sub=r_sub, n_pad=n_pad_c, f_chunk=Fc,
                variance=(cfg.impurity == "variance"),
            )
        else:
            parent = _seg_sum_trees(sw, seg, n_nodes + 1)[:, :n_nodes]
        parent = _allred(parent)
        leaf = leaf.at[:, offset : offset + n_nodes].set(parent)
        pcount = _count(parent, cfg.impurity)           # (T, n_nodes)
        pimp = _impurity(parent, cfg.impurity)

        bsf = jax.vmap(
            lambda h, p, pc, pi, rf_: _best_splits_from_hist(
                h, p, pc, pi, rf_, nb, cfg
            )
        )

        if use_compact:
            hist_full = _allred(hist_full)
            if subset:
                realf_full = feats.transpose(0, 2, 1)   # (T, k_pad, n_nodes)
            else:
                realf_full = jnp.broadcast_to(
                    jnp.arange(d_hist, dtype=jnp.int32)[None, :, None],
                    (T, d_hist, n_nodes),
                )
            Fc2 = d_hist
            while Fc2 > 1 and Fc2 * n_nodes * nb * S > 4 * _HIST_BUDGET:
                Fc2 //= 2
            bg = jnp.full((T, n_nodes), -jnp.inf, dt)
            bf = jnp.zeros((T, n_nodes), jnp.int32)
            bb = jnp.zeros((T, n_nodes), jnp.int32)
            for c0 in range(0, d_hist, Fc2):
                g, f, b = bsf(
                    hist_full[:, c0 : c0 + Fc2], parent, pcount, pimp,
                    realf_full[:, c0 : c0 + Fc2],
                )
                upd = g > bg
                bg = jnp.where(upd, g, bg)
                bf = jnp.where(upd, f, bf)
                bb = jnp.where(upd, b, bb)
        else:
            if cfg.hist_strategy == "matmul":
                use_matmul = True
            elif cfg.hist_strategy in ("scatter", "compact"):
                use_matmul = False
            elif subset:
                use_matmul = False
            else:
                use_matmul = (
                    jax.default_backend() == "tpu"
                    and (2.0 * n_nodes * nb) < _SCATTER_EQ_FLOPS
                )

            hist_src = make_hist_src()
            budget = (1 << 25) if (subset and not use_matmul) else _HIST_BUDGET
            F = _chunk_features(d_hist, n_nodes, nb, S, budget)
            n_chunks = d_hist // F
            if use_matmul:
                C_lvl = min(_ROW_CHUNK, n)
                f_cap = max(1, (1 << 26) // (C_lvl * nb))
                f_cap = 1 << (f_cap.bit_length() - 1)
                F = min(F, f_cap)
                n_chunks = d_hist // F

            def _hist_scatter_b(binc, *, n_nodes, in_level, local, sw):
                """(T, F, n_nodes, nb, S) via ONE fused global scatter:
                tree t's (node, bin) cells live at segment offset
                t*(n_nodes*nb+1), so per (tree, feature, cell) the
                accumulation visits the same rows in the same order as
                the sequential _hist_scatter — bitwise identical."""
                F = binc.shape[-1]
                num = n_nodes * nb + 1
                bc = binc if binc.ndim == 3 else binc[None]
                ids = jnp.where(
                    in_level[:, :, None],
                    local[:, :, None] * nb + bc,
                    n_nodes * nb,
                )                                       # (T, n, F)
                gids = ids + (
                    num * jnp.arange(T, dtype=jnp.int32)
                )[:, None, None]
                gflat = gids.reshape(T * n, F)
                if S <= 16:
                    hist = jnp.stack(
                        [
                            jax.vmap(
                                lambda col, c=sw[:, :, s].reshape(
                                    T * n
                                ): jax.ops.segment_sum(
                                    c, col, num_segments=T * num
                                ),
                                in_axes=1,
                            )(gflat)                    # (F, T*num)
                            for s in range(S)
                        ],
                        axis=-1,
                    )                                   # (F, T*num, S)
                else:
                    swf = sw.reshape(T * n, S)
                    hist = jax.vmap(
                        lambda col: jax.ops.segment_sum(
                            swf, col, num_segments=T * num
                        ),
                        in_axes=1,
                    )(gflat)
                hist = hist.reshape(F, T, num, S)[:, :, : n_nodes * nb, :]
                return hist.reshape(F, T, n_nodes, nb, S).transpose(
                    1, 0, 2, 3, 4
                )

            def _hist_matmul_b(binc, *, n_nodes, in_level, local, sw):
                """(T, F, n_nodes, nb, S) via one-hot contractions. With
                shared bins (no subset) the T node-onehots stack into a
                single tall-skinny (T*n_nodes, C) x (C, F*nb) MXU matmul
                per stat — the tree-batched dispatch shape this builder
                exists for. Variance stats and per-tree bins (forced
                matmul + subset) use a T-batched dot_general instead:
                each batch element is exactly the sequential (n_nodes, C)
                x (C, F*nb) GEMM, preserving its accumulation order —
                the flat stacking changes the GEMM's M extent, which
                measurably perturbs f32 accumulation at the last ulp
                (integer one-hot stats are exact either way, so
                classification keeps the fused form)."""
                F = binc.shape[-1]
                C = min(_ROW_CHUNK, n)
                nc = -(-n // C)
                node_ar = jnp.arange(n_nodes, dtype=jnp.int32)
                bin_ar = jnp.arange(nb, dtype=jnp.int32)
                prec = (
                    lax.Precision.HIGHEST
                    if cfg.impurity == "variance"
                    else None
                )
                shared_bins = binc.ndim == 2

                def row_body(ri, acc):
                    start = jnp.minimum(ri * C, n - C)
                    loc = lax.dynamic_slice(local, (0, start), (T, C))
                    lvl = lax.dynamic_slice(in_level, (0, start), (T, C))
                    swc = lax.dynamic_slice(sw, (0, start, 0), (T, C, S))
                    fresh = (start + jnp.arange(C)) >= ri * C
                    Noh = (
                        (loc[:, :, None] == node_ar[None, None, :])
                        & lvl[:, :, None]
                        & fresh[None, :, None]
                    ).astype(dt)                        # (T, C, n_nodes)
                    if shared_bins and prec is None:
                        bcc = lax.dynamic_slice(binc, (start, 0), (C, F))
                        Boh = (
                            bcc[:, :, None] == bin_ar[None, None, :]
                        ).astype(dt).reshape(C, F * nb)
                        out = jnp.stack(
                            [
                                jnp.matmul(
                                    (Noh * swc[:, :, s][:, :, None])
                                    .transpose(0, 2, 1)
                                    .reshape(T * n_nodes, C),
                                    Boh,
                                    precision=prec,
                                ).reshape(T, n_nodes, F * nb)
                                for s in range(S)
                            ],
                            axis=-1,
                        )                               # (T, n_nodes, F*nb, S)
                    elif shared_bins:
                        bcc = lax.dynamic_slice(binc, (start, 0), (C, F))
                        Boh = jnp.broadcast_to(
                            (bcc[:, :, None] == bin_ar[None, None, :])
                            .astype(dt)
                            .reshape(C, F * nb)[None],
                            (T, C, F * nb),
                        )
                        out = jnp.stack(
                            [
                                lax.dot_general(
                                    (Noh * swc[:, :, s][:, :, None])
                                    .transpose(0, 2, 1),
                                    Boh,
                                    (((2,), (1,)), ((0,), (0,))),
                                    precision=prec,
                                )
                                for s in range(S)
                            ],
                            axis=-1,
                        )
                    else:
                        bcc = lax.dynamic_slice(
                            binc, (0, start, 0), (T, C, F)
                        )
                        Boh = (
                            bcc[:, :, :, None] == bin_ar
                        ).astype(dt).reshape(T, C, F * nb)
                        out = jnp.stack(
                            [
                                lax.dot_general(
                                    (Noh * swc[:, :, s][:, :, None])
                                    .transpose(0, 2, 1),
                                    Boh,
                                    (((2,), (1,)), ((0,), (0,))),
                                    precision=prec,
                                )
                                for s in range(S)
                            ],
                            axis=-1,
                        )
                    return acc + out

                acc = lax.fori_loop(
                    0, nc, row_body,
                    jnp.zeros((T, n_nodes, F * nb, S), dt),
                )
                return acc.reshape(T, n_nodes, F, nb, S).transpose(
                    0, 2, 1, 3, 4
                )

            def chunk_body(carry, ci, *, n_nodes=n_nodes, parent=parent,
                           pcount=pcount, pimp=pimp, feats=feats, F=F,
                           in_level=in_level, local=local, sw=sw,
                           use_matmul=use_matmul, subset=subset,
                           hist_src=hist_src):
                bg, bf, bb = carry
                if subset:
                    binc = lax.dynamic_slice(
                        hist_src, (0, 0, ci * F), (T, n, F)
                    ).astype(jnp.int32)
                else:
                    binc = lax.dynamic_slice(
                        hist_src, (0, ci * F), (n, F)
                    ).astype(jnp.int32)
                make = _hist_matmul_b if use_matmul else _hist_scatter_b
                hist = make(
                    binc, n_nodes=n_nodes, in_level=in_level,
                    local=local, sw=sw,
                )
                hist = _allred(hist)
                if subset:
                    realf = lax.dynamic_slice(
                        feats, (0, 0, ci * F), (T, n_nodes, F)
                    ).transpose(0, 2, 1)                # (T, F, n_nodes)
                else:
                    realf = jnp.broadcast_to(
                        (ci * F + jnp.arange(F, dtype=jnp.int32))
                        [None, :, None],
                        (T, F, n_nodes),
                    )
                g, f, b = bsf(hist, parent, pcount, pimp, realf)
                upd = g > bg
                return (
                    jnp.where(upd, g, bg),
                    jnp.where(upd, f, bf),
                    jnp.where(upd, b, bb),
                ), None

            init = (
                jnp.full((T, n_nodes), -jnp.inf, dt),
                jnp.zeros((T, n_nodes), jnp.int32),
                jnp.zeros((T, n_nodes), jnp.int32),
            )
            (bg, bf, bb), _ = lax.scan(
                chunk_body, init, jnp.arange(n_chunks)
            )

        do_split = (
            jnp.isfinite(bg)
            & (bg >= max(cfg.min_info_gain, 1e-9))
            & (pcount >= cfg.min_samples_split)
        )                                               # (T, n_nodes)
        feat = feat.at[:, offset : offset + n_nodes].set(
            jnp.where(do_split, bf, -1)
        )
        thr_bin = thr_bin.at[:, offset : offset + n_nodes].set(bb)
        gains = gains.at[:, offset : offset + n_nodes].set(
            jnp.where(do_split, bg, jnp.zeros_like(bg))
        )

        lc = jnp.clip(local, 0, n_nodes - 1)
        row_feat = jnp.take_along_axis(bf, lc, axis=1)  # (T, n)
        if use_contract:
            row_bin = jax.vmap(
                lambda rf_: _contract_gather(packed, rf_[:, None])[:, 0]
            )(row_feat)
        else:
            row_bin = jax.vmap(
                lambda rf_: jnp.take_along_axis(
                    bins, jnp.clip(rf_, 0, d_pad - 1)[:, None], axis=1
                )[:, 0].astype(jnp.int32)
            )(row_feat)
        go_right = (row_bin > jnp.take_along_axis(bb, lc, axis=1)).astype(
            jnp.int32
        )
        child = 2 * node + 1 + go_right
        moves = in_level & jnp.take_along_axis(do_split, lc, axis=1)
        node = jnp.where(moves, child, node)

    out = {
        "feature": feat,
        "threshold_bin": thr_bin,
        "leaf_stats": leaf,
        "gain": gains,
    }
    if return_rows:
        out["node"] = node
    return out


def _build_trees_batched(
    bins: jax.Array,    # (n, d_pad) uint8
    stats: jax.Array,   # (n, S) float
    valid: jax.Array,   # (n,) float row mask
    keys: jax.Array,    # (T, 2) uint32
    cfg: ForestConfig,
) -> Dict[str, jax.Array]:
    """RF front half of the batched builder: per-tree bootstrap weights as
    a leading batch axis. RNG goes through ``lax.map`` of the sequential
    builder's exact split/poisson calls, so every tree draws identical
    weights to ``_build_tree(key)`` — the root of the bit-identity
    guarantee."""
    n = bins.shape[0]
    dt = stats.dtype
    kk = lax.map(jax.random.split, keys)                # (T, 2, 2)
    kb, kf = kk[:, 0], kk[:, 1]
    if cfg.bootstrap:
        logical = jnp.clip(
            jnp.cumsum(valid.astype(jnp.int32)) - 1, 0, n - 1
        )
        draws = lax.map(
            lambda k: jax.random.poisson(k, 1.0, (n,)), kb
        ).astype(dt)                                    # (T, n)
        w = draws[:, logical] * valid[None, :]
    else:
        w = jnp.broadcast_to(valid.astype(dt), (keys.shape[0], n))
    sw = stats[None] * w[:, :, None]                    # (T, n, S)
    return _grow_trees_batched(bins, sw, kf, cfg)


# ---------------------------------------------------------------------------
# forest build over the mesh
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("mesh", "cfg", "gather", "tree_batch")
)
def build_forest(
    bins: jax.Array,   # (N_pad, d_pad) uint8, dp-sharded
    mask: jax.Array,   # (N_pad,) float, dp-sharded
    stats: jax.Array,  # (N_pad, S) float, dp-sharded
    keys: jax.Array,   # (n_dp, trees_per_device, 2) uint32, dp-sharded
    *,
    mesh: Mesh,
    cfg: ForestConfig,
    gather: bool = False,
    tree_batch: int = 1,
) -> Dict[str, jax.Array]:
    """Each device grows ``trees_per_device`` trees; the stacked forest
    materializes via the out-sharding — the analog of the reference's
    allGather of serialized treelite bytes (``tree.py:319-366``).

    ``gather=False`` matches the reference's semantics exactly: each tree
    sees only its worker's row partition (the per-worker local cuRF fit,
    ``tree.py:269-402``), which costs tree quality as worker count grows.
    ``gather=True`` is the TPU-first improvement the reference cannot
    afford over NCCL: one ICI ``all_gather`` of the uint8 binned matrix
    (n x d bytes — 33 MB at 131k x 256, ~3 GB at the 1M x 3000 reference
    shape) gives every tree the FULL dataset, making quality independent
    of worker count while growth stays collective-free."""

    def per_device(bins_l, mask_l, stats_l, keys_l):
        if gather:
            bins_l = lax.all_gather(bins_l, DP_AXIS, axis=0, tiled=True)
            mask_l = lax.all_gather(mask_l, DP_AXIS, axis=0, tiled=True)
            stats_l = lax.all_gather(stats_l, DP_AXIS, axis=0, tiled=True)
        kl = keys_l[0]
        t_local = kl.shape[0]
        if tree_batch > 1 and t_local % tree_batch == 0:
            # tree-batched growth: (G, B, 2) key batches, B trees per
            # level dispatch (bit-identical to the sequential path —
            # see _grow_trees_batched)
            out = lax.map(
                lambda kb: _build_trees_batched(
                    bins_l, stats_l, mask_l, kb, cfg
                ),
                kl.reshape(t_local // tree_batch, tree_batch, 2),
            )
            return jax.tree_util.tree_map(
                lambda a: a.reshape((t_local,) + a.shape[2:]), out
            )
        return lax.map(
            lambda k: _build_tree(bins_l, stats_l, mask_l, k, cfg), kl
        )

    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(LAYOUT.rows(), LAYOUT.rows(), LAYOUT.rows(), LAYOUT.rows()),
        out_specs=LAYOUT.rows(),
        check_vma=False,
    )(bins, mask, stats, keys)


# ---------------------------------------------------------------------------
# inference
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("max_depth", "use_contract"))
def forest_apply(
    X: jax.Array,        # (n, d)
    feat: jax.Array,     # (T, M) int32, -1 = leaf
    thr: jax.Array,      # (T, M) raw-space thresholds (x >= thr -> right)
    *,
    max_depth: int,
    use_contract: bool | None = None,  # None = backend/width heuristic;
                                       # explicit value for cross-branch tests
) -> jax.Array:
    """Leaf index per (tree, row): vectorized level-synchronous descent.

    Per (tree, level) the descent needs two per-row values from the node
    tables (split feature, threshold) and one from X. TPU element
    gathers run ~1e8/s while ROW gathers are width-flat, so the tables
    ride as one (M, 2) f32 table gathered whole rows (feature ids < 2^24
    are f32-exact), and the X lookup becomes a dense lane contraction at
    moderate d. Measured v5e at 131k rows x 56 trees x depth 13:
    4.6 s -> 0.72 s (scripts history, round 4)."""
    n, d = X.shape

    # dense X-lane contraction beats take_along_axis up to ~1k features
    # (n*d compare-select work vs n serialized element gathers); fall
    # back to the gather past that, and everywhere off-TPU
    if use_contract is None:
        use_contract = jax.default_backend() == "tpu" and d <= 1024
    iota_d = jnp.arange(d, dtype=jnp.int32)

    def one_tree(tb):
        def body(_, node):
            g = tb[node]                         # (n, 2) one row gather
            nf = g[:, 0].astype(jnp.int32)
            tv = g[:, 1]
            if use_contract:
                sel = nf[:, None] == iota_d[None, :]
                xv = jnp.where(sel, X, 0.0).sum(axis=1)
            else:
                xv = jnp.take_along_axis(
                    X, jnp.clip(nf, 0, d - 1)[:, None], axis=1
                )[:, 0]
            go_right = (xv >= tv).astype(jnp.int32)
            child = 2 * node + 1 + go_right
            return jnp.where(nf < 0, node, child)

        return lax.fori_loop(0, max_depth, body, jnp.zeros((n,), jnp.int32))

    # table dtype: at least f32 (feature ids are exact only below 256 in
    # bf16 / 2048 in f16 — narrow thresholds widen losslessly instead),
    # and f64 thresholds stay f64 so boundary decisions are unperturbed
    tdt = jnp.promote_types(thr.dtype, jnp.float32)
    tbl = jnp.stack([feat.astype(tdt), thr.astype(tdt)], axis=-1)
    return jax.vmap(one_tree)(tbl)


# The lane-shuffle byte-gather kernel measures ~1e11 lane-gathers/s in
# isolation, but engaging it in the descent loses badly (161 ms -> ~500 ms
# for the bench forest, single or batched pallas_call alike): the call
# boundary de-fuses the surrounding pipeline. Opt-in knob kept for future
# toolchains; the compare-select contraction is the default. Read ONCE at
# import (the callers-outside-jit rule: an env read inside the traced
# functions would be silently ignored on jit cache hits; a module-level
# read is likewise cache-safe — the value is fixed per process).
_RF_BYTE_GATHER = bool(envspec.get("TPUML_RF_BYTE_GATHER"))


# --- two-hop subtree descent (bin space, zero per-row gathers) -------------
#
# The level-synchronous descent above pays one (n,2)-row gather per
# (tree, level): T*depth*n ~ 95M gathered rows at the bench shape, and the
# chip's gather engine tops out near 4e8 rows/s — an architectural wall
# ~25x short of GPU FIL-class inference (reference tree.py:557-591). The
# two-hop formulation removes per-row gathers entirely by exploiting the
# full-binary-tree layout (node i's children at 2i+1/2i+2, levels laid out
# contiguously, so every level-L slice reshapes to (2^k1, 2^(L-k1)) per
# level-k1 subtree):
#
#   hop 1 (levels 0..k1-1): the root subtree is SHARED by all rows, so its
#     2^k1-1 tests evaluate as ONE bf16 matmul of the binned rows against
#     the subtree's feature one-hot (bin ids and feature ids are small
#     ints — exact in bf16), then k1 arithmetic bit-navigation steps;
#   hop 2 (levels k1..D): each row's level-k1 subtree is one of 2^k1, so
#     its (feature, threshold) table arrives by a one-hot contraction over
#     the 2^k1 axis on the MXU (again exact small ints), the row-specific
#     feature bins come from the word-packed contraction gather, and k2
#     more bit-navigation steps reach the leaf. Leaf values are selected
#     the same way (f32 one-hot contraction + lane select).
#
# All comparisons happen in BIN space (x >= edges[f,b]  <=>  bin(x) > b,
# the exact training-side routing rule), so results are bit-identical to
# the raw-threshold descent wherever the model carries its bin tables.


def _navigate(enc, steps, L):
    """Heap-local descent over payload array enc (n, L) int32, heap order:
    enc[i] = 0 at a leaf (stop) else 1 + go_right_bit, so each step is
    ``i -> 2i + enc[i]`` while enc[i] > 0.

    The step-s lookup touches only the depth-s heap slice
    ``enc[:, 2^s-1 : 2^(s+1)-1]`` — a width-2^s lane one-hot — so total
    select work across all steps is one full pass over enc (n*L elements)
    instead of steps * n * L. Rows frozen at a shallower depth (i < lo)
    are guarded from reading a clipped lane. Returns (i, stopped_early):
    rows that complete all `steps` land at index >= L = 2^steps - 1."""
    n = enc.shape[0]
    i = jnp.zeros((n,), jnp.int32)
    for s in range(steps):
        lo = (1 << s) - 1
        w = 1 << s
        sl = lax.slice_in_dim(enc, lo, lo + w, axis=1)
        il = jnp.clip(i - lo, 0, w - 1)
        lanes = jnp.arange(w, dtype=jnp.int32)
        e = jnp.where(lanes[None, :] == il[:, None], sl, 0).sum(axis=1)
        e = jnp.where(i >= lo, e, 0)
        i = jnp.where(e > 0, 2 * i + e, i)
    return i, i < L


def _twohop_group(xb16, packed, feat_g, thr_g, val_g, *, max_depth, d):
    """One tree-group pass of the two-hop descent.

    xb16 (n, d) bf16 bins; packed (n, d/4) i32; feat_g (G, M) i32;
    thr_g (G, M) i32; val_g (G, M, V) f32 or None. Returns
    (leaf_ids (G, n) i32, values (n, V) f32 summed over the group or None).
    """
    n = xb16.shape[0]
    G, M = feat_g.shape
    D = max_depth
    k1 = max(min(7, D), D - 6)
    k2 = D - k1
    n1 = (1 << k1) - 1          # hop-1 internal candidate nodes 0..n1-1
    iota_d = jnp.arange(d, dtype=jnp.int32)
    from .rf_pallas import packed_byte_gather_many, packed_byte_gather_ok

    words = packed.shape[1]
    Wg = max(64, words)
    nint = (1 << k2) - 1 if k2 > 0 else 0
    use_bg = k2 > 0 and _RF_BYTE_GATHER and packed_byte_gather_ok(
        n, words, nint
    )
    if use_bg and words < Wg:
        packed = jnp.pad(packed, ((0, 0), (0, Wg - words)))

    leaf_ids = []
    vals_sum = None
    # phase A (per tree): hop-1 navigation + hop-2 table rows + byte indices
    ph = []
    for g in range(G):
        feat_t = feat_g[g]
        thr_t = thr_g[g]
        # ---- hop 1: shared root subtree
        f1 = feat_t[:n1]                                    # (n1,)
        oh1 = (f1[:, None] == iota_d[None, :]).astype(jnp.bfloat16)
        tests1 = jax.lax.dot_general(
            xb16, oh1, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                   # (n, n1)
        bits1 = (tests1 > thr_t[:n1].astype(jnp.float32)).astype(jnp.int32)
        enc1 = (1 + bits1) * (f1 >= 0)[None, :].astype(jnp.int32)
        i1, done1 = _navigate(enc1, k1, n1)
        if k2 == 0:
            leaf_ids.append(i1)
            if val_g is not None:
                v = val_g[g][i1]                            # (n, V) row gather
                vals_sum = v if vals_sum is None else vals_sum + v
            continue

        l7 = jnp.clip(i1 - n1, 0, (1 << k1) - 1)            # subtree id
        # ---- hop 2: per-subtree local tables, heap order m = 2^delta-1+j.
        # The per-row table read is ONE row gather from a tiny
        # (2^k1, 2*nint) table: a (n, 2^k1) one-hot matmul of the same
        # selection measures ~4 ms/tree at ANY precision (~2 TF/s
        # effective on the skinny shape) while the gather engine does
        # these rows in ~0.3 ms/tree — gathers win 10x here.
        sub_f = []
        sub_t = []
        for delta in range(k2):
            off = (1 << (k1 + delta)) - 1
            cnt = 1 << (k1 + delta)
            sh = (1 << k1, 1 << delta)
            sub_f.append(feat_t[off : off + cnt].reshape(sh))
            sub_t.append(thr_t[off : off + cnt].reshape(sh))
        tbl2 = jnp.concatenate(sub_f + sub_t, axis=1)       # (2^k1, 2*nint)
        rrow = tbl2[l7]                                     # (n, 2*nint)
        rfeat = rrow[:, :nint]
        rthr = rrow[:, nint:]
        ridx = jnp.clip(rfeat, 0, d - 1)
        ph.append((i1, done1, l7, rfeat, rthr, ridx))

    if k2 == 0:
        return jnp.stack(leaf_ids, axis=0), vals_sum

    # phase B: ONE batched lane-shuffle gather for the whole group (per-tree
    # pallas_call dispatches measured ~6 ms of overhead each inside a jitted
    # forest evaluation; the contraction fallback costs ~70 ms per forest)
    if use_bg:
        idx_all = jnp.stack(
            [jnp.pad(p[5], ((0, 0), (0, Wg - nint))) for p in ph]
        )                                                   # (G, n, Wg)
        xv_all = packed_byte_gather_many(packed, idx_all)   # (G, n, Wg)

    # phase C (per tree): hop-2 navigation + leaf/value resolution
    for g, (i1, done1, l7, rfeat, rthr, ridx) in enumerate(ph):
        if use_bg:
            xv = xv_all[g][:, :nint]
        else:
            xv = _contract_gather(packed, ridx)             # (n, nint) i32
        bits2 = ((xv > rthr) & (rfeat >= 0)).astype(jnp.int32)
        enc2 = (1 + bits2) * (rfeat >= 0).astype(jnp.int32)
        enc2 = jnp.where(done1[:, None], 0, enc2)
        m, _ = _navigate(enc2, k2, nint)
        # done1 rows keep i1; others: global id from (l7, local heap m)
        delta = jnp.zeros_like(m)
        for j in range(1, k2 + 1):
            delta = delta + (m + 1 >= (1 << j)).astype(jnp.int32)
        pd = jnp.left_shift(jnp.int32(1), delta)            # 2^delta
        j_local = m - (pd - 1)
        gid = ((1 << k1) * pd - 1) + l7 * pd + j_local
        leaf = jnp.where(done1, i1, gid)
        leaf_ids.append(leaf)

        if val_g is not None:
            v = val_g[g][leaf]                              # (n, V) row gather
            vals_sum = v if vals_sum is None else vals_sum + v

    return jnp.stack(leaf_ids, axis=0), vals_sum


def _twohop_drive(xb, feat, thr_bin, values, *, max_depth, group):
    """Shared driver for the two-hop descent: byte-gather row alignment,
    bf16 cast + word packing, tree-group loop, and row unpadding. With
    ``values`` None returns stacked (T, n) leaf ids; otherwise the (n, V)
    value sum over trees."""
    from .rf_pallas import _GATHER_BLOCK

    T = feat.shape[0]
    n0 = xb.shape[0]
    if _RF_BYTE_GATHER and jax.default_backend() == "tpu":
        # block-align rows so the Pallas lane-gather gate engages
        xb = jnp.pad(xb, ((0, (-n0) % _GATHER_BLOCK), (0, 0)))
    xb16 = xb.astype(jnp.bfloat16)
    packed = _pack_bins(xb)
    ids_out = []
    acc = None
    for g0 in range(0, T, group):
        ids, v = _twohop_group(
            xb16, packed, feat[g0 : g0 + group],
            thr_bin[g0 : g0 + group],
            None if values is None else values[g0 : g0 + group],
            max_depth=max_depth, d=xb.shape[1],
        )
        ids_out.append(ids)
        if values is not None:
            acc = v if acc is None else acc + v
    if values is None:
        return jnp.concatenate(ids_out, axis=0)[:, :n0]
    return acc[:n0]


@functools.partial(jax.jit, static_argnames=("max_depth", "group"))
def forest_apply_bins(
    xb: jax.Array,       # (n, d_pad) uint8 bin ids
    feat: jax.Array,     # (T, M) int32, -1 = leaf
    thr_bin: jax.Array,  # (T, M) int32 (bin(x) > thr_bin -> right)
    *,
    max_depth: int,
    group: int = 8,
) -> jax.Array:
    """Leaf node index per (tree, row) via the two-hop subtree descent."""
    return _twohop_drive(
        xb, feat, thr_bin, None, max_depth=max_depth, group=group
    )


@functools.partial(jax.jit, static_argnames=("max_depth", "group"))
def rf_eval_bins(
    xb: jax.Array,       # (n, d_pad) uint8 bin ids
    feat: jax.Array,     # (T, M) int32, -1 = leaf
    thr_bin: jax.Array,  # (T, M) int32
    values: jax.Array,   # (T, M, V) f32 per-node leaf stats
    *,
    max_depth: int,
    group: int = 8,
) -> jax.Array:
    """Sum over trees of each tree's leaf value vector, (n, V)."""
    return _twohop_drive(
        xb, feat, thr_bin, values, max_depth=max_depth, group=group
    )


@functools.partial(
    jax.jit, static_argnames=("max_depth", "group", "pred_dtype")
)
def rf_classify_bins(
    xb: jax.Array,       # (n, d_pad) uint8 bin ids
    feat: jax.Array,
    thr_bin: jax.Array,
    leaf_prob: jax.Array,  # (T, M, C) normalized leaf distributions
    *,
    max_depth: int,
    group: int = 8,
    pred_dtype=None,
):
    """Spark RF vote semantics via the two-hop bin-space descent: the
    summed-over-trees leaf distribution arrives directly from
    ``rf_eval_bins`` — no (T, n, C) materialization. ``group`` bounds the
    per-tree-group transients (smaller = leaner alongside big residents).
    ``pred_dtype`` sets the prediction dtype (legacy ``rf_classify``
    returns predictions in X.dtype; callers pass their row dtype here to
    keep that contract — default float32 for compatibility)."""
    raw = rf_eval_bins(
        xb, feat, thr_bin, leaf_prob, max_depth=max_depth, group=group
    )
    prob = raw / feat.shape[0]
    pred = jnp.argmax(raw, axis=1).astype(pred_dtype or jnp.float32)
    return pred, prob, raw


@functools.partial(jax.jit, static_argnames=("max_depth", "group"))
def rf_regress_bins(
    xb: jax.Array,
    feat: jax.Array,
    thr_bin: jax.Array,
    leaf_value: jax.Array,  # (T, M) per-tree leaf means
    *,
    max_depth: int,
    group: int = 8,
) -> jax.Array:
    s = rf_eval_bins(
        xb, feat, thr_bin, leaf_value[..., None], max_depth=max_depth,
        group=group,
    )
    return s[:, 0] / leaf_value.shape[0]


# ---------------------------------------------------------------------------
# FIL-style packed-forest inference engine
# ---------------------------------------------------------------------------
#
# The two-hop bins path above still walks trees one at a time inside each
# group: per tree one skinny hop-1 matmul, one table gather, one
# contraction gather — each a separate XLA op with its own fusion
# boundary, ~70 ms of contraction gathers plus per-op overhead at the
# bench forest. cuML's FIL closes the same gap on GPU by re-laying the
# forest into an interleaved SoA blob and descending a row tile through
# ALL trees per level in lockstep. The TPU analog here:
#
#   * ``pack_forest`` (host, once per model) re-lays the heap-ordered
#     (T, M) tensors breadth-first into lane-width-padded SoA blocks:
#     hop-1 root subtrees as (T_pad, n1) slabs driving ONE all-tree bf16
#     one-hot matmul, and hop-2 per-subtree (feature, threshold) tables
#     as (T_pad * 2^k1, 64) slabs the traversal kernel row-selects on
#     the MXU.
#   * ``rf_pallas.packed_traverse`` fuses the whole hop-2 phase — table
#     row-select, lane-shuffle byte gather of the row's feature bins,
#     masked bit-navigation, global-leaf-id arithmetic — for every tree
#     into ONE pallas_call per row block, removing the per-tree dispatch
#     and gather-engine costs that dominated the bins path.
#   * leaf payloads are then accumulated tree-sequentially in the exact
#     order ``_twohop_drive`` uses (group-8 partial sums), so packed
#     results are BIT-IDENTICAL to the bins path: leaf indices are
#     integers (exact by construction) and the f32 payload sums
#     reassociate identically.


class PackedForest(NamedTuple):
    """Breadth-first interleaved SoA forest layout (``pack_forest``).

    Arrays are plain numpy (host) so models can persist them via the
    standard attribute round-trip and ship them to device once per
    process. ``feat2``/``thr2`` are empty (0, 64) when ``k2 == 0`` —
    forests shallow enough that hop-1 alone reaches every leaf.
    """

    feat1: np.ndarray    # (T_pad, n1) int32 hop-1 root subtrees, -1 = leaf
    thr1: np.ndarray     # (T_pad, n1) int32 bin thresholds
    feat2: np.ndarray    # (T_pad * 2^k1, 64) int32 hop-2 tables, -1 pad
    thr2: np.ndarray     # (T_pad * 2^k1, 64) int32
    n_trees: int         # real tree count T (payload accumulation bound)
    k1: int              # hop-1 depth (root-subtree levels)
    k2: int              # hop-2 depth (per-subtree levels)
    max_depth: int


def pack_forest(feat, thr_bin, *, max_depth: int) -> PackedForest:
    """Re-lay a trained forest for lockstep traversal (host, once).

    ``feat``/``thr_bin`` are the (T, M) heap-ordered int32 tensors the
    builder emits. The split point k1/k2 matches ``_twohop_group``
    exactly (k1 = max(min(7, D), D-6)) so packed descent reproduces the
    same leaf indices. Trees are padded to a multiple of 8 with all-leaf
    sentinels (feat = -1): padding trees navigate to leaf 0 and are
    sliced out of payload accumulation. The hop-2 tables interleave
    per-subtree rows — table row ``t * 2^k1 + s`` holds subtree ``s`` of
    tree ``t`` with its ``2^k2 - 1`` internal nodes in heap-local
    breadth-first order along lanes (lane m = local heap slot m), padded
    to the 64-lane shuffle width with leaf sentinels.
    """
    feat = np.asarray(feat, dtype=np.int32)
    thr = np.asarray(thr_bin, dtype=np.int32)
    T, M = feat.shape
    D = int(max_depth)
    k1 = max(min(7, D), D - 6)
    k2 = D - k1
    n1 = (1 << k1) - 1
    T_pad = -(-T // 8) * 8
    featp = np.pad(feat, ((0, T_pad - T), (0, 0)), constant_values=-1)
    thrp = np.pad(thr, ((0, T_pad - T), (0, 0)))
    feat1 = np.ascontiguousarray(featp[:, :n1])
    thr1 = np.ascontiguousarray(thrp[:, :n1])
    LANES = 64  # nint = 2^k2 - 1 <= 63 always (k2 <= 6)
    if k2 == 0:
        feat2 = np.full((0, LANES), -1, np.int32)
        thr2 = np.zeros((0, LANES), np.int32)
    else:
        K1 = 1 << k1
        f2 = np.full((T_pad, K1, LANES), -1, np.int32)
        t2 = np.zeros((T_pad, K1, LANES), np.int32)
        for delta in range(k2):
            off = (1 << (k1 + delta)) - 1
            cnt = 1 << (k1 + delta)
            w = 1 << delta
            lo = (1 << delta) - 1  # heap-local lane offset of this level
            f2[:, :, lo : lo + w] = featp[:, off : off + cnt].reshape(
                T_pad, K1, w
            )
            t2[:, :, lo : lo + w] = thrp[:, off : off + cnt].reshape(
                T_pad, K1, w
            )
        feat2 = f2.reshape(T_pad * K1, LANES)
        thr2 = t2.reshape(T_pad * K1, LANES)
    return PackedForest(
        feat1=feat1, thr1=thr1, feat2=feat2, thr2=thr2,
        n_trees=T, k1=k1, k2=k2, max_depth=D,
    )


def _packed_hop1(xb16, feat1, thr1, *, k1):
    """All-tree hop-1: every root subtree's tests in ONE bf16 one-hot
    matmul (exact — bin and feature ids are small ints) followed by a
    tree-batched bit-navigation. Returns (n, T_pad) int32 heap indices;
    rows stopped at a hop-1 leaf hold index < 2^k1 - 1."""
    n, d = xb16.shape
    T_pad, n1 = feat1.shape
    iota_d = jnp.arange(d, dtype=jnp.int32)
    f1 = feat1.reshape(T_pad * n1)
    oh1 = (f1[:, None] == iota_d[None, :]).astype(jnp.bfloat16)
    tests1 = lax.dot_general(
        xb16, oh1, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # (n, T_pad*n1)
    thr_f = thr1.reshape(T_pad * n1).astype(jnp.float32)
    bits1 = (tests1 > thr_f[None, :]).astype(jnp.int32)
    enc1 = ((1 + bits1) * (f1 >= 0)[None, :].astype(jnp.int32)).reshape(
        n, T_pad, n1
    )
    i = jnp.zeros((n, T_pad), jnp.int32)
    for s in range(k1):
        lo = (1 << s) - 1
        w = 1 << s
        sl = lax.slice_in_dim(enc1, lo, lo + w, axis=2)   # (n, T, w)
        il = jnp.clip(i - lo, 0, w - 1)
        lanes = jnp.arange(w, dtype=jnp.int32)
        e = jnp.where(lanes[None, None, :] == il[..., None], sl, 0).sum(
            axis=2
        )
        e = jnp.where(i >= lo, e, 0)
        i = jnp.where(e > 0, 2 * i + e, i)
    return i


def _packed_payload(leaf, values, *, n_trees, group):
    """Tree-sequential payload accumulation over packed leaf ids, in the
    EXACT association ``_twohop_drive`` uses (per-group partial sums in
    tree order, then sequential across groups) so packed f32 sums are
    bit-identical to the bins path's."""
    acc = None
    for g0 in range(0, n_trees, group):
        vals_sum = None
        for t in range(g0, min(g0 + group, n_trees)):
            v = values[t][leaf[:, t]]                    # (n, V) row gather
            vals_sum = v if vals_sum is None else vals_sum + v
        acc = vals_sum if acc is None else acc + vals_sum
    return acc


@functools.partial(
    jax.jit, static_argnames=("k1", "k2", "max_depth", "interpret")
)
def forest_apply_packed(
    xb: jax.Array,       # (n, d_pad) uint8 bin ids
    feat1: jax.Array,    # (T_pad, n1) int32
    thr1: jax.Array,     # (T_pad, n1) int32
    feat2: jax.Array,    # (T_pad * 2^k1, 64) int32
    thr2: jax.Array,     # (T_pad * 2^k1, 64) int32
    *,
    k1: int,
    k2: int,
    max_depth: int,
    interpret=None,
) -> jax.Array:
    """Global leaf index per (row, tree): (n, T_pad) int32, lockstep over
    all trees. Callers gate on ``rf_pallas.packed_traverse_ok`` first —
    this function assumes the traversal kernel lowers (or interprets)."""
    from .rf_pallas import TRAVERSE_BLOCK, packed_traverse

    n0, d_pad = xb.shape
    n = -(-n0 // TRAVERSE_BLOCK) * TRAVERSE_BLOCK
    if n > n0:
        xb = jnp.pad(xb, ((0, n - n0), (0, 0)))
    xb16 = xb.astype(jnp.bfloat16)
    i1 = _packed_hop1(xb16, feat1, thr1, k1=k1)          # (n, T_pad)
    if k2 == 0:
        return i1[:n0]
    packed = _pack_bins(xb)                              # (n, d_pad/4)
    leaf = packed_traverse(
        packed, i1, feat2, thr2, k1=k1, k2=k2, d_pad=d_pad,
        interpret=interpret,
    )
    return leaf[:n0]


@functools.partial(
    jax.jit, static_argnames=("k1", "k2", "max_depth", "group", "interpret")
)
def rf_eval_packed(
    xb: jax.Array,
    feat1: jax.Array,
    thr1: jax.Array,
    feat2: jax.Array,
    thr2: jax.Array,
    values: jax.Array,   # (T, M, V) per-node leaf payloads (REAL trees)
    *,
    k1: int,
    k2: int,
    max_depth: int,
    group: int = 8,
    interpret=None,
) -> jax.Array:
    """Sum over trees of each tree's leaf payload vector, (n, V) — the
    packed-engine equivalent of ``rf_eval_bins``, bit-identical to it
    (same leaf indices, same f32 accumulation order)."""
    leaf = forest_apply_packed(
        xb, feat1, thr1, feat2, thr2, k1=k1, k2=k2, max_depth=max_depth,
        interpret=interpret,
    )
    return _packed_payload(
        leaf, values, n_trees=values.shape[0], group=group
    )


@functools.partial(
    jax.jit,
    static_argnames=("k1", "k2", "max_depth", "group", "pred_dtype",
                     "interpret"),
)
def rf_classify_packed(
    xb: jax.Array,
    feat1: jax.Array,
    thr1: jax.Array,
    feat2: jax.Array,
    thr2: jax.Array,
    leaf_prob: jax.Array,  # (T, M, C) normalized leaf distributions
    *,
    k1: int,
    k2: int,
    max_depth: int,
    group: int = 8,
    pred_dtype=None,
    interpret=None,
):
    """Spark RF vote semantics through the packed engine — same contract
    (and bit-identical outputs) as ``rf_classify_bins``."""
    raw = rf_eval_packed(
        xb, feat1, thr1, feat2, thr2, leaf_prob,
        k1=k1, k2=k2, max_depth=max_depth, group=group,
        interpret=interpret,
    )
    prob = raw / leaf_prob.shape[0]
    pred = jnp.argmax(raw, axis=1).astype(pred_dtype or jnp.float32)
    return pred, prob, raw


@functools.partial(
    jax.jit, static_argnames=("k1", "k2", "max_depth", "group", "interpret")
)
def rf_regress_packed(
    xb: jax.Array,
    feat1: jax.Array,
    thr1: jax.Array,
    feat2: jax.Array,
    thr2: jax.Array,
    leaf_value: jax.Array,  # (T, M) per-tree leaf means
    *,
    k1: int,
    k2: int,
    max_depth: int,
    group: int = 8,
    interpret=None,
) -> jax.Array:
    s = rf_eval_packed(
        xb, feat1, thr1, feat2, thr2, leaf_value[..., None],
        k1=k1, k2=k2, max_depth=max_depth, group=group,
        interpret=interpret,
    )
    return s[:, 0] / leaf_value.shape[0]


@functools.partial(jax.jit, static_argnames=("max_depth",))
def rf_classify(
    X: jax.Array,
    feat: jax.Array,
    thr: jax.Array,
    leaf_prob: jax.Array,  # (T, M, C) per-tree normalized leaf distributions
    *,
    max_depth: int,
):
    """Spark RF vote semantics: rawPrediction = sum over trees of each
    tree's normalized leaf class distribution; probability = raw/numTrees."""
    leaves = forest_apply(X, feat, thr, max_depth=max_depth)        # (T, n)
    probs = jax.vmap(lambda lp, lv: lp[lv])(leaf_prob, leaves)      # (T, n, C)
    raw = probs.sum(axis=0)
    prob = raw / feat.shape[0]
    pred = jnp.argmax(raw, axis=1).astype(X.dtype)
    return pred, prob, raw


@functools.partial(jax.jit, static_argnames=("max_depth",))
def rf_regress(
    X: jax.Array,
    feat: jax.Array,
    thr: jax.Array,
    leaf_value: jax.Array,  # (T, M) per-tree leaf means
    *,
    max_depth: int,
) -> jax.Array:
    leaves = forest_apply(X, feat, thr, max_depth=max_depth)
    vals = jax.vmap(lambda lv, ix: lv[ix])(leaf_value, leaves)      # (T, n)
    return vals.mean(axis=0)


def next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())
