"""In-situ knockout attribution INSIDE _hist_compact: full 13-level tree
builds with pieces of the compact histogram path stubbed out (wrong
results, cost-indicative).

  full     — real _hist_compact
  nosort   — identity permutation (skips lax.sort)
  noglue   — fake uniform node runs (skips searchsorted/table machinery)
  nogather — kernel fed the first n_pad rows unsorted (skips swq/binq gathers)
  nokernel — zero partials (skips the Pallas kernel)
  nosegsum — partials summed flat (skips the wide per-node segment_sum)
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.ops import tree_kernels as tk
from spark_rapids_ml_tpu.ops.rf_pallas import BLOCK_ROWS, subblock_hist

N, D, K, NB, S, DEPTH = 131072, 256, 16, 128, 2, 13


def hist_compact_knock(hist_src, seg, sw, *, n_nodes, nb, r_sub, n_pad,
                       f_chunk, knock):
    n, F = hist_src.shape
    S = sw.shape[1]
    n_sb = n_pad // r_sub
    iota = jnp.arange(n, dtype=jnp.int32)
    if knock == "nosort":
        keys_s, perm = seg, iota
    else:
        keys_s, perm = lax.sort((seg, iota), num_keys=1)
    if knock == "noglue":
        # fake uniform runs: node i owns rows [i*n//n_nodes, ...)
        per = n_pad // n_sb
        seg_sb = jnp.minimum(
            jnp.arange(n_sb, dtype=jnp.int32) * n_nodes // n_sb, n_nodes - 1)
        src2 = perm[jnp.minimum(jnp.arange(n_pad) % n, n - 1)]
        pvalid = jnp.arange(n_pad) < n
        seg_red = seg_sb
    else:
        starts = jnp.searchsorted(
            keys_s, jnp.arange(n_nodes + 1, dtype=jnp.int32), side="left"
        ).astype(jnp.int32)
        lens = starts[1:] - starts[:-1]
        plen = -(-lens // r_sub) * r_sub
        pstart = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(plen)])
        sb_pos = jnp.arange(n_sb, dtype=jnp.int32) * r_sub
        seg_sb = jnp.searchsorted(pstart[1:], sb_pos, side="right").astype(jnp.int32)
        sbc = jnp.clip(seg_sb, 0, n_nodes - 1)
        tbl = jnp.stack([starts[:-1], pstart[:-1], lens], axis=1)
        tbl_rows = jnp.broadcast_to(tbl[sbc][:, None, :], (n_sb, r_sub, 3)).reshape(n_pad, 3)
        pos = jnp.arange(n_pad, dtype=jnp.int32)
        off = pos - tbl_rows[:, 1]
        src = tbl_rows[:, 0] + off
        pvalid = (off < tbl_rows[:, 2]) & (
            jnp.broadcast_to(seg_sb[:, None], (n_sb, r_sub)).reshape(n_pad) < n_nodes)
        src2 = perm[jnp.clip(src, 0, n - 1)]
        seg_red = jnp.where(seg_sb < n_nodes, seg_sb, n_nodes)
    if knock == "nogather":
        swq = jnp.broadcast_to(sw[:1], (n_pad, S)) * pvalid[:, None]
        binq = jnp.broadcast_to(hist_src[:1].astype(jnp.int32), (n_pad, F))
    else:
        swq = sw[src2] * pvalid[:, None].astype(sw.dtype)
        binq = hist_src[src2].astype(jnp.int32)
    if knock == "nokernel":
        partials = jnp.zeros((n_sb, S, F * nb), jnp.float32) + swq.sum() * 1e-30 + binq.sum() * 1e-30
    else:
        partials = subblock_hist(binq, swq, n_bins=nb, r_sub=r_sub,
                                 variance=False)
    if knock == "nosegsum":
        tot = partials.sum(axis=0, keepdims=True)
        hist_nodes = jnp.broadcast_to(tot, (n_nodes, S, F * nb)).reshape(
            n_nodes, S, F, nb) + seg_red[0] * 1e-30
    else:
        hist_nodes = jax.ops.segment_sum(
            partials.reshape(n_sb, S * F * nb), seg_red,
            num_segments=n_nodes + 1)[:n_nodes].reshape(n_nodes, S, F, nb)
    parent = hist_nodes[:, :, 0, :].sum(axis=-1)
    return hist_nodes.transpose(2, 0, 3, 1), parent


def build_tree(bins, stats, valid, key, cfg, knock):
    n, d_pad = bins.shape
    S, nb = cfg.n_stats, cfg.n_bins
    M = tk.max_nodes(cfg.max_depth)
    dt = stats.dtype
    kb, kf = jax.random.split(jnp.asarray(key))
    w = valid.astype(dt)
    sw = stats * w[:, None]
    feat = jnp.full((M,), -1, jnp.int32)
    thr_bin = jnp.zeros((M,), jnp.int32)
    leaf = jnp.zeros((M, S), dt)
    node = jnp.zeros((n,), jnp.int32)
    packed = tk._pack_bins(bins)
    for level in range(cfg.max_depth + 1):
        offset = (1 << level) - 1
        n_nodes = 1 << level
        local = node - offset
        in_level = (local >= 0) & (local < n_nodes)
        seg = jnp.where(in_level, local, n_nodes).astype(jnp.int32)
        if level == cfg.max_depth:
            parent = jax.ops.segment_sum(sw, seg, num_segments=n_nodes + 1)[:n_nodes]
            leaf = leaf.at[offset:offset + n_nodes].set(parent)
            break
        r = jax.random.uniform(jax.random.fold_in(kf, level), (n_nodes, D))
        feats = lax.top_k(r, K)[1].astype(jnp.int32)
        lc0 = jnp.clip(local, 0, n_nodes - 1)
        hist_src = tk._contract_gather(packed, feats[lc0])
        r_sub = tk._compact_r_sub(n, n_nodes, BLOCK_ROWS, S)
        n_pad_c = -(-(n + (n_nodes + 1) * r_sub) // BLOCK_ROWS) * BLOCK_ROWS
        hist_full, parent = hist_compact_knock(
            hist_src, seg, sw, n_nodes=n_nodes, nb=nb, r_sub=r_sub,
            n_pad=n_pad_c, f_chunk=K, knock=knock)
        leaf = leaf.at[offset:offset + n_nodes].set(parent)
        pcount = tk._count(parent, cfg.impurity)
        pimp = tk._impurity(parent, cfg.impurity)
        bg, bf, bb = tk._best_splits_from_hist(
            hist_full, parent, pcount, pimp, feats.T, nb, cfg)
        do_split = jnp.isfinite(bg) & (bg >= 1e-9) & (pcount >= cfg.min_samples_split)
        feat = feat.at[offset:offset + n_nodes].set(jnp.where(do_split, bf, -1))
        thr_bin = thr_bin.at[offset:offset + n_nodes].set(bb)
        row_feat = bf[lc0]
        row_bin = tk._contract_gather(packed, row_feat[:, None])[:, 0]
        go_right = (row_bin > bb[lc0]).astype(jnp.int32)
        child = 2 * node + 1 + go_right
        moves = in_level & do_split[lc0]
        node = jnp.where(moves, child, node)
    return {"feature": feat, "threshold_bin": thr_bin, "leaf_stats": leaf}


def main():
    rng = np.random.default_rng(0)
    bins = jnp.asarray(rng.integers(0, NB, size=(N, D), dtype=np.uint8))
    yc = rng.integers(0, 2, size=N)
    stats = jnp.asarray(np.eye(2, dtype=np.float32)[yc])
    valid = jnp.ones((N,), jnp.float32)
    cfg = tk.ForestConfig(max_depth=DEPTH, n_bins=NB, n_features=D, n_stats=S,
        impurity="gini", k_features=K, min_samples_leaf=1, min_info_gain=0.0,
        min_samples_split=2, bootstrap=False)
    bins_reps = [jax.block_until_ready(jnp.asarray((np.asarray(bins)+(r+1)) % NB, jnp.uint8)) for r in range(3)]
    for knock in ["full", "nosort", "noglue", "nogather", "nokernel", "nosegsum"]:
        # each knockout variant IS a distinct program; compiled once per
        # variant and reused across the timed reps  # tpuml: ignore[TPU003]
        fn = jax.jit(lambda b, kn=knock: build_tree(
            b, stats, valid, jax.random.PRNGKey(1), cfg, kn))
        jax.block_until_ready(fn(bins))
        best = 1e30
        for rr in range(3):
            t0 = time.perf_counter()
            out = fn(bins_reps[rr])
            np.asarray(out["feature"])
            best = min(best, time.perf_counter() - t0)
        print(f"{knock:9s}: {best*1e3:7.1f} ms/tree")


if __name__ == "__main__":
    main()
