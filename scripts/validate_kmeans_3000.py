"""On-chip validation: resident KMeans at 1M x 3000 (the reference
benchmark shape) fits ONE v5e with ~1x matrix bytes.

Round 2 finding: at lane-unaligned d (3000 % 128 != 0) XLA inserts a
defensive full copy of X around the Lloyd while_loop — 2x matrix HBM, an
OOM at this shape on a 16 GB chip. Round 3 zero-pads features to the
lane multiple at ingestion (HBM-free: the minor dim is physically tiled
to 128 anyway). This script proves the fix at the real shape: generates
1M x 3000 ON DEVICE (~12.3 GB f32 logical, 12.6 GB padded), runs a
short Lloyd fit through the SAME kernel the estimator uses with the
estimator's padded layout, and prints peak HBM.

Run on the chip: python scripts/validate_kmeans_3000.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_rapids_ml_tpu.utils.platform import pin_platform  # noqa: E402

pin_platform(sys.argv[1] if len(sys.argv) > 1 else None)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from spark_rapids_ml_tpu.ops.kmeans_kernels import kmeans_lloyd  # noqa: E402
from spark_rapids_ml_tpu.parallel.mesh import make_mesh  # noqa: E402

N, D, K = int(os.environ.get("VK_ROWS", 1_000_000)), 3000, 100
D_PAD = -(-D // 128) * 128  # the estimator's lane padding (3072)
CSIZE = 4096
mesh = make_mesh(len(jax.devices()))
n_dp = mesh.shape["dp"]
n_pad = -(-N // (CSIZE * n_dp)) * (CSIZE * n_dp)

sh = NamedSharding(mesh, P("dp"))


def gen(key):
    from jax import lax

    unit = n_pad // 16

    def body(i, X):
        blk = jax.random.normal(
            jax.random.fold_in(key, i), (unit, D_PAD), jnp.float32
        )
        # zero the padding columns (the estimator pads with zeros)
        blk = blk * (jnp.arange(D_PAD) < D).astype(jnp.float32)[None, :]
        return lax.dynamic_update_slice_in_dim(X, blk, i * unit, 0)

    X = lax.fori_loop(0, 16, body, jnp.zeros((n_pad, D_PAD), jnp.float32))
    mask = (jnp.arange(n_pad) < N).astype(jnp.float32)
    return X, mask


X, mask = jax.jit(gen, out_shardings=(sh, sh))(jax.random.key(0))
jax.block_until_ready(X)
centers0 = jax.random.normal(jax.random.key(1), (K, D_PAD), jnp.float32)
centers0 = centers0 * (jnp.arange(D_PAD) < D).astype(jnp.float32)[None, :]

t0 = time.perf_counter()
centers, cost, it = kmeans_lloyd(
    X, mask, centers0, mesh=mesh, csize=CSIZE, max_iter=3, tol=0.0
)
np.asarray(cost)
t = time.perf_counter() - t0

stats = jax.devices()[0].memory_stats() or {}
line = {
    "metric": "kmeans_1m_3000_resident",
    "rows": N,
    "cols": D,
    "cols_padded": D_PAD,
    "k": K,
    "iters_plus_cost": int(it) + 1,
    "seconds": round(t, 2),
    "matrix_gb": round(n_pad * D_PAD * 4 / 1e9, 2),
    "peak_hbm_gb": round(int(stats.get("peak_bytes_in_use", 0)) / 1e9, 2),
    "device": jax.devices()[0].device_kind,
    "cost": float(np.asarray(cost)),
}
print(json.dumps(line))
