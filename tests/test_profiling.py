"""Tracing/profiling subsystem (SURVEY §5: the reference wraps phases in
NVTX ranges, ``RapidsRowMatrix.scala:62,70``; here phases are
``jax.profiler`` trace annotations + TensorBoard captures)."""

import glob
import logging

import numpy as np

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.utils.profiling import annotate, timed, trace


def test_fit_under_profile_capture(tmp_path, rng):
    """A fit inside a profiler capture produces a TensorBoard trace and
    identical results (annotations must never perturb numerics)."""
    X = rng.normal(size=(120, 6)).astype(np.float32)
    df = DataFrame({"features": X})
    plain = PCA(k=2, num_workers=2).fit(df)
    with trace(str(tmp_path)):
        traced = PCA(k=2, num_workers=2).fit(df)
    np.testing.assert_allclose(traced.components_, plain.components_)
    assert glob.glob(str(tmp_path / "plugins" / "profile" / "*")), (
        "no TensorBoard profile written"
    )


def test_trace_noop_without_dir():
    with trace(None):
        pass  # transparent


def test_annotate_and_timed(caplog):
    logger = logging.getLogger("tpuml-test")
    with caplog.at_level(logging.DEBUG, logger="tpuml-test"):
        with annotate("phase"), timed(logger, "phase"):
            np.zeros(3).sum()
    assert any("phase took" in r.message for r in caplog.records)
