"""Stage-by-stage timing of the UMAP fit at the bench shape (65k x 256).

Run on the real TPU:  python scripts/umap_profile.py
Stages: knn graph -> self-drop -> fuzzy set -> spectral init -> row
adjacency -> SGD (``optimize_embedding_rows``). Round-5 reference
timings: knn ~1 s, fuzzy 0.4 s warm, spectral 0.25 s, SGD 2.9 s.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.models.umap import knn_brute
from spark_rapids_ml_tpu.ops.knn_kernels import resolve_knn_topk
from spark_rapids_ml_tpu.ops.umap_kernels import (
    build_row_adjacency,
    default_n_epochs,
    find_ab_params,
    fuzzy_simplicial_set,
    optimize_embedding_rows,
    spectral_init,
)


def main():
    n = int(os.environ.get("UMAP_PROF_ROWS", 65536))
    d = 256
    k = 15
    rng = np.random.default_rng(3)
    centers = rng.normal(size=(32, d)).astype(np.float32) * 4.0
    lab = rng.integers(0, 32, size=n)
    Xh = (centers[lab] + rng.normal(size=(n, d))).astype(np.float32)
    topk = resolve_knn_topk()

    t0 = time.perf_counter()
    Xd = jnp.asarray(Xh)
    dists, idx = knn_brute(Xd, Xd, k=k + 1, topk_impl=topk)
    np.asarray(dists)
    t_compile = time.perf_counter() - t0
    Xd2 = jnp.asarray(Xh * np.float32(1 + 1e-6))
    t0 = time.perf_counter()
    dists, idx = knn_brute(Xd2, Xd2, k=k + 1, topk_impl=topk)
    idx_np = np.asarray(idx)
    dists_np = np.asarray(dists)
    t_knn = time.perf_counter() - t0
    print(f"knn: compile+run {t_compile:.2f}s warm(incl fetch) {t_knn:.2f}s")

    t0 = time.perf_counter()
    self_mask = idx_np == np.arange(n)[:, None]
    has_self = self_mask.any(axis=1)
    drop_col = np.where(has_self, self_mask.argmax(axis=1), k)
    keep = np.ones_like(self_mask)
    keep[np.arange(n), drop_col] = False
    knn_i = idx_np[keep].reshape(n, k)
    knn_d = dists_np[keep].reshape(n, k)
    print(f"self-drop {time.perf_counter() - t0:.2f}s")

    t0 = time.perf_counter()
    heads, tails, weights = fuzzy_simplicial_set(knn_i, knn_d, 1.0, 1.0)
    print(f"fuzzy set {time.perf_counter() - t0:.2f}s  edges={len(heads)}")

    t0 = time.perf_counter()
    emb0 = spectral_init(heads, tails, weights, n, 2, 42)
    print(f"spectral init {time.perf_counter() - t0:.2f}s")

    t0 = time.perf_counter()
    rh, tp, pp = build_row_adjacency(heads, tails, weights, n, K=24)
    print(f"row adjacency {time.perf_counter() - t0:.2f}s  R={len(rh)}")

    a, b = find_ab_params(1.0, 0.1)
    n_epochs = default_n_epochs(n)
    args = (
        jnp.asarray(emb0), jnp.asarray(emb0), jnp.asarray(rh),
        jnp.asarray(tp), jnp.asarray(pp), jax.random.PRNGKey(42),
    )
    kw = dict(n_epochs=n_epochs, a=float(a), b=float(b), gamma=1.0,
              initial_alpha=1.0, negative_sample_rate=5, self_table=True)
    t0 = time.perf_counter()
    emb = np.asarray(optimize_embedding_rows(*args, **kw))
    t_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    emb = np.asarray(
        optimize_embedding_rows(args[0] * jnp.float32(1 + 1e-6), *args[1:], **kw)
    )
    t_sgd = time.perf_counter() - t0
    print(f"sgd: cold {t_cold:.2f}s warm {t_sgd:.2f}s "
          f"({n_epochs} epochs -> {t_sgd / n_epochs * 1e3:.1f} ms/epoch)")


if __name__ == "__main__":
    main()
