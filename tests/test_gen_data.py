"""Data-generator + benchmark-harness tests (reference model:
``/root/reference/python/benchmark/test_gen_data.py``, 489 LoC: validates
rank/correlation/label structure of the synthetic datasets)."""

import subprocess
import sys

import numpy as np
import pytest

from benchmark.gen_data import (
    gen_blobs,
    gen_classification,
    gen_low_rank_matrix,
    gen_regression,
    gen_sparse_regression,
    make_dataframe,
)


def test_blobs_cluster_structure():
    X, y = gen_blobs(2000, 8, centers=5, cluster_std=0.1, seed=1)
    assert X.shape == (2000, 8) and y.shape == (2000,)
    assert set(np.unique(y)) <= set(range(5))
    # within-cluster spread far below global spread
    global_std = X.std()
    within = np.mean([X[y == c].std(axis=0).mean() for c in np.unique(y)])
    assert within < global_std / 5


def test_low_rank_matrix_rank():
    X, y = gen_low_rank_matrix(500, 60, effective_rank=5, tail_strength=0.1, seed=0)
    assert y is None
    s = np.linalg.svd(X.astype(np.float64), compute_uv=False)
    # energy concentrates in the first ~effective_rank singular values
    assert s[:10].sum() / s.sum() > 0.55
    assert s[0] / s[30] > 3


def test_regression_recoverable_weights():
    X, y = gen_regression(3000, 20, n_informative=5, noise=0.1, seed=2)
    w, *_ = np.linalg.lstsq(X.astype(np.float64), y.astype(np.float64), rcond=None)
    pred = X @ w
    r2 = 1 - ((pred - y) ** 2).sum() / ((y - y.mean()) ** 2).sum()
    assert r2 > 0.99
    # exactly n_informative large weights
    assert (np.abs(w) > 1.0).sum() == 5


def test_classification_separable():
    X, y = gen_classification(2000, 12, n_classes=3, class_sep=3.0, seed=3)
    assert set(np.unique(y)) == {0.0, 1.0, 2.0}
    from sklearn.linear_model import LogisticRegression

    acc = LogisticRegression(max_iter=200).fit(X, y).score(X, y)
    assert acc > 0.9


def test_sparse_regression_density():
    X, y = gen_sparse_regression(1000, 50, density=0.1, seed=4)
    assert X.shape == (1000, 50)
    density = X.nnz / (1000 * 50)
    assert 0.05 < density < 0.15
    assert y.shape == (1000,)


def test_make_dataframe_and_parquet_roundtrip(tmp_path):
    df = make_dataframe("classification", 300, 6, seed=5)
    assert "features" in df and "label" in df
    path = str(tmp_path / "ds")
    df.write_parquet(path, rows_per_file=100)
    from spark_rapids_ml_tpu.data import DataFrame

    back = DataFrame.read_parquet(path)
    assert back.count() == 300
    np.testing.assert_allclose(back["features"], df["features"], rtol=1e-6)


def test_chunked_generation_deterministic():
    X1, y1 = gen_blobs(1000, 4, centers=3, seed=7)
    X2, y2 = gen_blobs(1000, 4, centers=3, seed=7)
    np.testing.assert_array_equal(X1, X2)
    np.testing.assert_array_equal(y1, y2)


@pytest.mark.parametrize(
    "algo,extra",
    [
        ("pca", ["--k", "3"]),
        ("logistic_regression", ["--maxIter", "20"]),
        pytest.param("kmeans", ["--k", "8", "--max_iter", "5"], marks=pytest.mark.slow),
        pytest.param("linear_regression", [], marks=pytest.mark.slow),
        pytest.param("random_forest_classifier", ["--numTrees", "4", "--maxDepth", "4"], marks=pytest.mark.slow),
        pytest.param("knn", ["--k", "5", "--num_queries", "50"], marks=pytest.mark.slow),
    ],
)
def test_benchmark_runner_smoke(algo, extra, tmp_path):
    """The harness must run end-to-end at smoke scale on the CPU mesh
    (reference CI smoke run: ``python/run_benchmark.sh:66-68``)."""
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    report = str(tmp_path / "report.csv")
    cmd = [
        sys.executable, "benchmark_runner.py", algo,
        "--num_rows", "400", "--num_cols", "8", "--num_runs", "1",
        "--num_chips", "2", "--report_path", report,
    ] + extra
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=600, env=env, cwd="/root/repo"
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "fit_time" in open(report).read()


# ---------------------------------------------------------------------------
# distributed generation
# ---------------------------------------------------------------------------


def test_gen_distributed_deterministic_across_worker_counts(tmp_path):
    """Output must depend only on (seed, file, group) — never the pool
    size (the reference's per-partition-seed invariant)."""
    from benchmark.gen_data_distributed import generate

    a = generate("blobs", 2500, 8, str(tmp_path / "a"), num_files=7,
                 num_procs=1, rows_per_group=512, seed=3, centers=5)
    b = generate("blobs", 2500, 8, str(tmp_path / "b"), num_files=7,
                 num_procs=2, rows_per_group=512, seed=3, centers=5)
    from spark_rapids_ml_tpu.data import DataFrame

    da = DataFrame.read_parquet(a)
    db = DataFrame.read_parquet(b)
    np.testing.assert_array_equal(
        np.asarray(da.column("features")), np.asarray(db.column("features"))
    )
    np.testing.assert_array_equal(
        np.asarray(da.column("label")), np.asarray(db.column("label"))
    )
    assert len(list((tmp_path / "a").glob("*.parquet"))) == 7


def test_gen_distributed_feeds_streaming_fit(tmp_path):
    """The generated parquet is directly consumable by the out-of-core fit
    (VERDICT: generation at benchmark scale -> streaming fit, end to end)."""
    from benchmark.gen_data_distributed import generate
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.feature import PCA
    from spark_rapids_ml_tpu.classification import LogisticRegression

    p = generate("low_rank_matrix", 4000, 16, str(tmp_path / "lr"),
                 num_files=5, num_procs=2, rows_per_group=700, seed=1,
                 effective_rank=2)
    scan = DataFrame.scan_parquet(p)
    m = PCA(k=4, num_workers=4, streaming=True, stream_chunk_rows=512).fit(scan)
    assert not scan.is_materialized()
    ev = np.asarray(m.explained_variance_)
    assert ev[0] > ev[3] * 2  # low-rank: decaying spectrum

    c = generate("classification", 3000, 10, str(tmp_path / "cls"),
                 num_files=4, num_procs=2, rows_per_group=640, seed=2,
                 n_classes=3, n_informative=4)
    scan2 = DataFrame.scan_parquet(c)
    lr = LogisticRegression(num_workers=4, streaming=True,
                            stream_chunk_rows=512, regParam=0.01).fit(scan2)
    assert lr.numClasses == 3
    assert not scan2.is_materialized()
