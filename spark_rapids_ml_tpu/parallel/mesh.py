"""Device mesh management — the TPU-native "cluster".

The reference's worker topology is 1 Spark barrier task = 1 GPU, with
NCCL joining them (``/root/reference/python/src/spark_rapids_ml/common/cuml_context.py:35-147``).
TPU-natively the topology is a ``jax.sharding.Mesh``: data parallelism maps
rows onto the ``dp`` axis; feature/model parallelism (used by wide-feature
Gram computations and multi-model fits) maps onto ``mp``. XLA inserts the
collectives (psum/all_gather) that NCCL provided in the reference.

Axis naming convention used across the framework:
  * ``dp`` — data parallel (rows of the design matrix)
  * ``mp`` — model parallel (features / trees / hyper-param sets)
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
MP_AXIS = "mp"


def default_device_count() -> int:
    return len(jax.devices())


@functools.lru_cache(maxsize=None)
def _cached_mesh(n_dp: int, n_mp: int) -> Mesh:
    devices = np.asarray(jax.devices()[: n_dp * n_mp]).reshape(n_dp, n_mp)
    return Mesh(devices, (DP_AXIS, MP_AXIS))


def _default_mp_budget() -> float:
    """Default HBM budget for one device's model-axis shard under
    ``TPUML_MESH_MP=auto``: a quarter of the device memory limit (4 GB
    when the backend reports none, e.g. the CPU test mesh) — the same
    convention as the gang-fit and tree-batch resolvers."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = float(stats.get("bytes_limit", 0.0))
    except Exception:
        limit = 0.0
    if limit <= 0.0:
        limit = float(16 << 30)
    return limit / 4.0


def resolve_mesh_mp(model_bytes: float = 0.0) -> int:
    """Resolved model-parallel degree for :func:`make_mesh` (1 = the 1-D
    row-sharded mesh, bit-identical to the pre-2-D behavior).

    ``TPUML_MESH_MP``: ``off`` (default) keeps mp=1, an integer pins the
    degree (clamped to the device count with a warning), ``auto`` picks
    the smallest power-of-two degree whose per-device model-axis shard
    (``model_bytes / mp`` — the caller's Gram-block / centroid-table /
    IVF-index estimate) fits the HBM budget (``TPUML_MESH_MP_BUDGET``,
    default a quarter of device memory).
    """
    from ..runtime import envspec

    raw = str(envspec.get("TPUML_MESH_MP")).strip().lower()
    if raw == "off":
        return 1
    avail = default_device_count()
    if raw == "auto":
        budget = envspec.get("TPUML_MESH_MP_BUDGET")
        budget = float(budget) if budget else _default_mp_budget()
        mp = 1
        while float(model_bytes) / mp > budget and mp * 2 <= avail:
            mp *= 2
    else:
        try:
            mp = int(raw)
        except ValueError:
            raise envspec.EnvSpecError(
                f"TPUML_MESH_MP={raw!r}: expected 'auto', 'off', or a "
                "positive integer"
            ) from None
        if mp < 1:
            raise envspec.EnvSpecError(
                f"TPUML_MESH_MP={mp}: mp degree must be >= 1"
            )
        if mp > avail:
            from ..utils.logging import get_logger

            get_logger("mesh").warning(
                "TPUML_MESH_MP=%d > %d devices; clamping mp to %d",
                mp, avail, avail,
            )
            mp = avail
    if mp > 1:
        from ..runtime import telemetry

        telemetry.record_hbm_estimate("mesh_mp", float(model_bytes) / mp)
    return mp


def make_mesh(num_workers: Optional[int] = None, mp: Optional[int] = None) -> Mesh:
    """Build a (dp, mp) mesh over the first ``num_workers * mp`` devices.

    ``num_workers`` defaults to all devices — *global* devices when a
    multi-process world is configured. ``mp`` defaults to the
    ``TPUML_MESH_MP`` resolution (:func:`resolve_mesh_mp`; 1 when the env
    is unset). Requesting more workers than devices available clamps down
    with a warning — the reference similarly clamps/validates against the
    cluster's GPU count (``params.py:377-409``).
    """
    from .context import ensure_distributed

    ensure_distributed()
    if mp is None:
        mp = resolve_mesh_mp()
    avail = default_device_count()
    if jax.process_count() > 1:
        # multi-process worlds always span the FULL device world: a mesh
        # that excludes one rank's devices would strand that rank outside
        # every collective (peers would hang, not error)
        full_dp = max(1, avail // mp)
        if num_workers is not None and num_workers != full_dp:
            from ..utils.logging import get_logger

            get_logger("mesh").warning(
                "num_workers=%d ignored in multi-process mode; using all "
                "%d global devices (dp=%d)", num_workers, avail, full_dp,
            )
        return _cached_mesh(full_dp, mp)
    if num_workers is None:
        num_workers = max(1, avail // mp)
    if num_workers * mp > avail:
        from ..utils.logging import get_logger

        get_logger("mesh").warning(
            "Requested %d workers x %d mp > %d devices; clamping dp to %d",
            num_workers, mp, avail, max(1, avail // mp),
        )
        num_workers = max(1, avail // mp)
    return _cached_mesh(num_workers, mp)


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 over dp; replicate over mp."""
    return NamedSharding(mesh, P(DP_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def col_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 1 over mp; replicate over dp — the SUMMA column-blocked
    layout of square (d, d) model-axis accumulators."""
    return NamedSharding(mesh, P(None, MP_AXIS))


def block_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 over mp; replicate over dp — feature/centroid/list
    blocks of model-axis state."""
    return NamedSharding(mesh, P(MP_AXIS))


def shard_cols(x: Any, mesh: Mesh) -> jax.Array:
    """``device_put`` a host/device array column-blocked over the mp axis
    (dim 1 of d x d accumulators). Dim 1 must divide by the mesh's mp
    degree; with mp=1 this is a plain replicated placement."""
    n_mp = mesh.shape[MP_AXIS]
    if x.shape[1] % n_mp:
        raise ValueError(
            f"dim 1 ({x.shape[1]}) does not divide the mesh mp degree "
            f"({n_mp}); pad the model axis before sharding"
        )
    return jax.device_put(x, col_sharding(mesh))


def fetch_blocked(arr: jax.Array, mesh: Mesh) -> np.ndarray:
    """Host-fetch a model-axis-blocked global array (column-blocked Gram,
    centroid/list blocks) as the full unsharded value.

    Single-process meshes read the addressable shards directly; a
    multi-host fetch reshards to fully-replicated first (one all_gather)
    so every process can assemble the complete value — the model-axis
    analog of :func:`fetch_global`.
    """
    if jax.process_count() <= 1:
        return np.asarray(arr)
    rep = _replicate_jit(mesh)(arr)
    return np.asarray(rep.addressable_shards[0].data)


def pad_rows(
    x: np.ndarray, multiple: int, pad_value: float = 0.0
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad dim-0 to a multiple of the dp size; returns (padded, mask).

    Static shapes are an XLA requirement: instead of the reference's
    ragged per-task partitions (``PartitionDescriptor``, ``utils.py:163-200``)
    we pad to an even shard and carry a row-validity mask that downstream
    reductions fold in (a masked psum replaces cuML's ragged allreduce).
    """
    n = x.shape[0]
    n_pad = (-n) % multiple
    mask = np.ones((n,), dtype=np.float32)
    if n_pad:
        pad_width = [(0, n_pad)] + [(0, 0)] * (x.ndim - 1)
        x = np.pad(x, pad_width, constant_values=pad_value)
        mask = np.pad(mask, (0, n_pad), constant_values=0.0)
    return x, mask


def shard_rows(
    x: np.ndarray, mesh: Mesh, row_multiple: int = 1
) -> Tuple[jax.Array, jax.Array]:
    """Pad + device_put a host array row-sharded over the dp axis.

    This is the data-plane replacement for the reference's Arrow-batch →
    cupy ingestion inside the barrier task (``core.py:717-741``).
    ``row_multiple`` > 1 additionally aligns each device's shard to that
    multiple (for kernels that scan rows in fixed-size chunks).
    Returns (sharded_x, sharded_mask).

    Multi-process: ``x`` is this process's local rows (each worker holds
    its partition, as each Spark barrier task held its Arrow batches).
    Processes agree on a common per-device row count via a host allgather
    — the ``PartitionDescriptor.build`` analog (``utils.py:163-200``) —
    pad locally, and assemble one global row-sharded array; the mask marks
    every process's padding rows invalid.
    """
    x = np.asarray(x)
    if jax.process_count() > 1:
        return _shard_rows_multiproc(x, mesh, row_multiple)
    n_dp = mesh.shape[DP_AXIS]
    xp, mask = pad_rows(x, n_dp * row_multiple)
    sh = row_sharding(mesh)
    xd = jax.device_put(xp, sh)
    md = jax.device_put(mask, sh)
    return xd, md


def _local_dp_devices(mesh: Mesh) -> int:
    """This process's dp-axis device count; validates the uniform-devices-
    per-process assumption the global shard layout math relies on (ranks
    must all derive the SAME per-device row count or their collective
    shapes diverge)."""
    nproc = jax.process_count()
    n_total = mesh.devices.size
    pidx = jax.process_index()
    n_local = sum(1 for d in mesh.devices.flat if d.process_index == pidx)
    n_mp = mesh.shape[MP_AXIS]
    if n_local == 0 or n_local % n_mp:
        raise ValueError(
            f"mesh dp axis does not evenly cover process {pidx}'s devices"
        )
    if n_local * nproc != n_total:
        raise ValueError(
            f"multi-process sharding requires a uniform device count per "
            f"process; process {pidx} has {n_local} of {n_total} devices "
            f"across {nproc} processes"
        )
    return n_local // n_mp


def _shard_rows_multiproc(
    x: np.ndarray, mesh: Mesh, row_multiple: int
) -> Tuple[jax.Array, jax.Array]:
    from jax.experimental import multihost_utils

    local_dp = _local_dp_devices(mesh)
    counts = np.asarray(
        multihost_utils.process_allgather(np.asarray([x.shape[0]]))
    ).ravel()
    if counts.max() == 0:
        raise ValueError("dataset is empty on every process")
    # common per-device shard rows: fits the largest local partition,
    # aligned to row_multiple
    per_dev = -(-int(counts.max()) // local_dp)
    per_dev = -(-per_dev // row_multiple) * row_multiple
    local_rows = per_dev * local_dp
    if x.shape[0] == 0:
        # a legitimately empty local partition contributes all-invalid rows
        xp = np.zeros((local_rows,) + x.shape[1:], x.dtype)
        mask = np.zeros((local_rows,), np.float32)
    else:
        xp, mask = pad_rows(x, local_rows)
    if xp.shape[0] != local_rows:
        raise ValueError(
            f"local rows {x.shape[0]} exceed the agreed shard {local_rows}"
        )
    n_dp = mesh.shape[DP_AXIS]
    global_rows = per_dev * n_dp
    sh = row_sharding(mesh)
    xd = jax.make_array_from_process_local_data(sh, xp, (global_rows,) + x.shape[1:])
    md = jax.make_array_from_process_local_data(sh, mask, (global_rows,))
    return xd, md


def shard_aligned(v: np.ndarray, mesh: Mesh, total_rows: int) -> jax.Array:
    """Shard a per-process 1-D array (labels/weights) with the same row
    layout as an existing ``shard_rows`` output of global padded length
    ``total_rows`` (padding rows zero-filled)."""
    v = np.asarray(v)
    if jax.process_count() <= 1:
        vp = np.pad(v, (0, total_rows - v.shape[0]))
        return jax.device_put(vp, row_sharding(mesh))
    local_rows = total_rows // jax.process_count()
    vp = np.pad(v, (0, local_rows - v.shape[0]))
    return jax.make_array_from_process_local_data(
        row_sharding(mesh), vp, (total_rows,)
    )


@functools.lru_cache(maxsize=None)
def _replicate_jit(mesh: Mesh):
    """One compiled reshard-to-replicated program per mesh — building the
    jit per call would retrace on every fetch (the cache keys on the
    callable object)."""
    return jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, P()))


@functools.lru_cache(maxsize=None)
def _gather_replicated_jit(mesh: Mesh):
    return jax.jit(
        lambda a, i: jnp.take(a, i, axis=0),
        out_shardings=NamedSharding(mesh, P()),
    )


def fetch_global(arr: jax.Array, mesh: Mesh) -> np.ndarray:
    """``np.asarray`` that also works for row-sharded multi-host arrays:
    reshard to fully-replicated (one all_gather over ICI/DCN) so every
    process can read the complete value."""
    if jax.process_count() <= 1:
        return np.asarray(arr)
    rep = _replicate_jit(mesh)(arr)
    return np.asarray(rep.addressable_shards[0].data)


def gather_rows_global(x: jax.Array, idx: np.ndarray, mesh: Mesh) -> np.ndarray:
    """Host-fetch selected rows of a (possibly multi-host) row-sharded
    matrix: device-side gather with a replicated output, then one fetch."""
    out = _gather_replicated_jit(mesh)(x, np.asarray(idx))
    if jax.process_count() <= 1:
        return np.asarray(out)
    return np.asarray(out.addressable_shards[0].data)


def global_row_count(n_local: int) -> int:
    """Total valid rows across the process world (local count if single)."""
    if jax.process_count() <= 1:
        return int(n_local)
    from jax.experimental import multihost_utils

    return int(
        np.asarray(multihost_utils.process_allgather(np.asarray([n_local]))).sum()
    )


def combine_label_summaries(local: np.ndarray) -> Dict[str, Any]:
    """Allgather + merge per-rank label-summary vectors.

    ``local`` encodes ``[is_empty, max, min, all_int, first, all_same,
    count]``; one wire format shared by the resident column scan
    (:func:`global_label_summary`) and the streaming label pass
    (``ops.streaming.streamed_label_stats``).
    """
    g = allgather_host(np.asarray(local))
    non_empty = g[g[:, 0] == 0.0]
    if len(non_empty) == 0:
        return {
            "y_max": -np.inf, "y_min": np.inf, "all_int": True,
            "all_same": True, "first": 0.0, "total": 0,
        }
    return {
        "y_max": float(non_empty[:, 1].max()),
        "y_min": float(non_empty[:, 2].min()),
        "all_int": bool(np.all(non_empty[:, 3] == 1.0)),
        "all_same": bool(
            np.all(non_empty[:, 5] == 1.0)
            and np.all(non_empty[:, 4] == non_empty[0, 4])
        ),
        "first": float(non_empty[0, 4]),
        "total": int(g[:, 6].sum()),
    }


def global_label_summary(y_local: np.ndarray) -> Dict[str, Any]:
    """World-wide label statistics from per-process label columns.

    Every rank must agree on label-derived compile-time constants
    (n_classes, degenerate single-label cases) or their collectives
    diverge; empty local partitions are legitimate and excluded.
    Returns ``{y_max, y_min, all_int, all_same, first, total}``.
    """
    y_local = np.asarray(y_local)
    empty = y_local.size == 0
    local = np.asarray(
        [
            1.0 if empty else 0.0,
            -np.inf if empty else float(y_local.max()),
            np.inf if empty else float(y_local.min()),
            1.0 if empty or bool(np.all(y_local == np.floor(y_local))) else 0.0,
            0.0 if empty else float(y_local[0]),
            1.0 if empty or bool(np.all(y_local == y_local[0])) else 0.0,
            float(y_local.size),
        ]
    )
    return combine_label_summaries(local)


def allgather_host(vals: np.ndarray) -> np.ndarray:
    """Host-value allgather across the process world: (k,) per process ->
    (nproc, k). Identity-with-leading-axis single-process. The out-of-band
    metadata exchange of the reference's ``BarrierTaskContext.allGather``
    (``cuml_context.py:75-103``)."""
    vals = np.atleast_1d(np.asarray(vals))
    if jax.process_count() <= 1:
        return vals[None, :]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(vals))


def host_file_shard(
    files: Any,
    *,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    mp: int = 1,
    devices_per_process: Optional[int] = None,
) -> List[Any]:
    """This host's round-robin subset of the ingest file list.

    Per-host sharded ingest: with the streaming data plane partition-local
    (see :func:`local_mesh`), N hosts reading the SAME parquet directory
    would each decode every file and N-fold overcount the global
    statistics at the allreduce. Round-robin assignment
    (``files[group::n_groups]``) makes the subsets a disjoint cover, so N
    hosts pull N files concurrently and the existing
    :func:`allreduce_sum_host` of partials is exact. Round-robin (not
    contiguous blocks) keeps per-host byte counts balanced when file sizes
    trend across the directory (time-partitioned writers).

    The shard key is the process's **dp replica group**, not its bare
    rank: on a 2-D mesh where one process owns fewer devices than the mp
    degree, the ``mp // devices_per_process`` consecutive processes
    spanning one dp row replicate the same logical data rows and must
    read the SAME file subset — keying off rank alone would hand them
    disjoint sets that disagree across the model axis. With ``mp=1`` (or
    processes owning whole dp rows) every process is its own group and
    the assignment reduces to the historical ``files[rank::nprocs]``.

    ``process_index`` / ``process_count`` / ``devices_per_process``
    default to the live jax process world; tests and ``dryrun_multichip``
    override them to validate the assignment without a real multi-host
    world. Identity when the world has one group.
    """
    idx = jax.process_index() if process_index is None else int(process_index)
    n = jax.process_count() if process_count is None else int(process_count)
    if n < 1 or not (0 <= idx < n):
        raise ValueError(f"invalid process world: index {idx} of {n}")
    n_mp = int(mp)
    if n_mp < 1:
        raise ValueError(f"invalid mp degree: {n_mp}")
    dpp = (
        jax.local_device_count()
        if devices_per_process is None
        else int(devices_per_process)
    )
    if dpp < 1:
        raise ValueError(f"invalid devices_per_process: {dpp}")
    # processes spanning one dp row (row-major device order: a process
    # owning < mp devices shares its dp row with the next ones)
    procs_per_group = max(1, n_mp // dpp)
    if n % procs_per_group:
        raise ValueError(
            f"process world of {n} does not divide into mp replica groups "
            f"of {procs_per_group} (mp={n_mp}, devices_per_process={dpp})"
        )
    files = list(files)
    n_groups = n // procs_per_group
    if n_groups == 1:
        return files
    return files[idx // procs_per_group :: n_groups]


def local_mesh(mp: int = 1) -> Mesh:
    """A mesh over THIS process's devices only.

    The streaming data plane is partition-local (each worker streams its
    chunks through its own chips, like each reference barrier task streams
    its Arrow batches through its GPU); cross-process combination happens
    at the sufficient-statistics level via :func:`allreduce_sum_host`.
    """
    devs = jax.local_devices()
    n_dp = max(1, len(devs) // mp)
    return Mesh(np.asarray(devs[: n_dp * mp]).reshape(n_dp, mp), (DP_AXIS, MP_AXIS))


def allreduce_sum_host(*arrays: Any) -> Tuple[np.ndarray, ...]:
    """Elementwise-sum each array across the process world (host path).

    The explicit allreduce of per-partition partials — exactly the role
    NCCL allreduce played inside cuML's MG fit. Single-process: identity.
    Sums in float64 for exactness; returns each result in its input dtype.
    """
    if jax.process_count() <= 1:
        return tuple(np.asarray(a) for a in arrays)
    parts = [np.asarray(a) for a in arrays]
    flat = np.concatenate([p.astype(np.float64).ravel() for p in parts])
    total = allgather_host(flat).sum(axis=0)
    out = []
    off = 0
    for p in parts:
        out.append(
            total[off : off + p.size].reshape(p.shape).astype(p.dtype)
        )
        off += p.size
    return tuple(out)


def allgather_host_blobs(blob: bytes) -> List[bytes]:
    """Gather one opaque byte blob per process, rank-ordered.

    The metadata-exchange primitive behind
    ``telemetry.aggregate_metrics``: each rank JSON-encodes its metric
    snapshot, the blobs ride a padded uint8 allgather (counts first, so
    uneven payloads trim exactly), and every rank gets the full list to
    merge locally. Single-process: ``[blob]``.
    """
    a = np.frombuffer(blob, np.uint8)
    if jax.process_count() <= 1:
        return [blob]
    counts = allgather_host(np.asarray([a.shape[0]])).ravel().astype(int)
    maxc = max(int(counts.max()), 1)
    padded = np.zeros((maxc,), np.uint8)
    padded[: a.shape[0]] = a
    gathered = allgather_host(padded)
    return [
        gathered[p][: counts[p]].tobytes() for p in range(len(counts))
    ]


def allgather_ragged_rows(a: np.ndarray) -> np.ndarray:
    """Concatenate every process's rows in rank order (uneven partitions
    padded through a host allgather, then trimmed) — the multi-host analog
    of coalescing a dataset to one node."""
    counts = allgather_host(np.asarray([a.shape[0]])).ravel().astype(int)
    maxc = int(counts.max())
    padded = np.zeros((maxc,) + a.shape[1:], a.dtype)
    padded[: a.shape[0]] = a
    gathered = allgather_host(padded)
    return np.concatenate([gathered[p][: counts[p]] for p in range(len(counts))])


def allgather_ragged_rows_exact(a: np.ndarray) -> np.ndarray:
    """Dtype-exact ragged row gather: moves raw bytes (the plain gather
    rides jax arrays, which canonicalize int64/float64 to 32-bit when x64
    is off) and views them back as the input dtype."""
    a = np.ascontiguousarray(a)
    row_shape = a.shape[1:]
    # explicit widths, not -1: reshape(-1) is ambiguous for 0-row inputs
    # (a rank with an empty partition must still join the collective)
    row_elems = int(np.prod(row_shape, dtype=np.int64)) if row_shape else 1
    flat = a.reshape(a.shape[0], row_elems)
    as_bytes = flat.view(np.uint8).reshape(a.shape[0], row_elems * a.itemsize)
    g = allgather_ragged_rows(as_bytes)
    return (
        np.ascontiguousarray(g).view(a.dtype).reshape((len(g),) + row_shape)
    )


def object_string_kind(a: np.ndarray):
    """np.str_/np.bytes_ for an all-str / all-bytes object array; raises
    TypeError otherwise. Scans EVERY element: a single stray Python int
    would silently stringify (corrupting joins), and ranks sampling
    different prefixes could disagree on raising vs entering a collective
    (deadlock) — so no shortcut sampling."""
    kinds = {type(v) for v in a.ravel()}
    if kinds <= {str, np.str_}:
        return np.str_
    if kinds <= {bytes, np.bytes_}:
        return np.bytes_
    raise TypeError(
        f"cannot exchange object column with element types {kinds}; "
        "use a numeric or string dtype"
    )


def unify_string_width(a: np.ndarray) -> np.ndarray:
    """Cast an object/str/bytes array to a fixed-width dtype whose width is
    agreed across the process world (the byte-moving collectives need every
    rank to view rows at the same itemsize). Numeric arrays pass through."""
    if a.dtype.kind not in "OUS":
        return a
    if a.dtype.kind == "O":
        a = np.asarray(a, dtype=object_string_kind(a))
    else:
        a = np.asarray(a, dtype=np.str_ if a.dtype.kind == "U" else np.bytes_)
    unit = np.dtype(a.dtype.kind + "1").itemsize
    w_local = max(1, a.dtype.itemsize // unit)
    w = int(allgather_host(np.asarray([w_local])).max())
    return a.astype(f"{a.dtype.kind}{w}")


def allgather_ragged_any(a: np.ndarray) -> np.ndarray:
    """:func:`allgather_ragged_rows_exact` that also accepts string/object
    columns (width-unified first so every rank's byte view agrees)."""
    return allgather_ragged_rows_exact(unify_string_width(np.asarray(a)))


def local_row_block(arr: jax.Array) -> np.ndarray:
    """This process's rows of a row-sharded array, assembled from its
    addressable shards in row order — no collective, and no assumption
    that the dp device order is process-contiguous."""
    shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])
