"""Fault-tolerant fit runtime tests (``spark_rapids_ml_tpu/runtime/``).

The acceptance contract: an injected mid-fit fault (``TPUML_FAULT_SPEC``)
followed by a refit with ``TPUML_CKPT_DIR`` set produces a final model
same-seed-equivalent to the uninterrupted fit — for KMeans (streamed
Lloyd), LogisticRegression (host L-BFGS), and UMAP (segmented epoch
loop) — and with no resilience env set the whole runtime is inert
(no files, zero counters, unchanged fit path).
"""

import os
import traceback

import numpy as np
import pytest

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.runtime import counters
from spark_rapids_ml_tpu.runtime.checkpoint import (
    FitCheckpointer,
    array_digest,
    params_hash,
)
from spark_rapids_ml_tpu.runtime.faults import (
    FaultInjector,
    FaultSpecError,
    InjectedFault,
    InjectedResourceExhausted,
    SimulatedPreemption,
    fault_site,
    fault_sites_active,
    parse_fault_spec,
    reset_faults,
)
from spark_rapids_ml_tpu.runtime.retry import (
    backoff_schedule,
    is_resource_exhausted,
    with_retries,
)

_RES_ENV = (
    "TPUML_CKPT_DIR",
    "TPUML_CKPT_EVERY",
    "TPUML_RETRIES",
    "TPUML_BACKOFF_MS",
    "TPUML_FAULT_SPEC",
    "TPUML_CV_FAILFAST",
)


@pytest.fixture(autouse=True)
def _clean_resilience_state(monkeypatch):
    for var in _RES_ENV:
        monkeypatch.delenv(var, raising=False)
    reset_faults()
    counters.reset()
    yield
    reset_faults()
    counters.reset()


# ---------------------------------------------------------------------------
# fault-spec grammar + injector semantics
# ---------------------------------------------------------------------------


def test_fault_spec_parses_full_grammar():
    entries = parse_fault_spec(
        "ingest:chunk:3:raise, sgd:epoch:5:preempt,init:connect:2:oom"
    )
    assert entries == [
        ("ingest:chunk", 3, "raise"),
        ("sgd:epoch", 5, "preempt"),
        ("init:connect", 2, "oom"),
    ]


@pytest.mark.parametrize(
    "bad",
    [
        "sgd:epoch:raise",            # missing index
        "bogus:site:0:raise",         # unknown site
        "sgd:epoch:0:explode",        # unknown action
        "sgd:epoch:x:raise",          # non-integer index
        "sgd:epoch:-1:raise",         # negative index
    ],
)
def test_fault_spec_rejects_malformed(bad):
    with pytest.raises(FaultSpecError):
        parse_fault_spec(bad)


def test_injector_fires_exactly_once_at_index():
    inj = FaultInjector("sgd:epoch:2:raise")
    inj.hit("sgd:epoch")
    inj.hit("sgd:epoch")
    with pytest.raises(InjectedFault):
        inj.hit("sgd:epoch")
    # spent: subsequent passes (the resumed fit) sail through
    for _ in range(10):
        inj.hit("sgd:epoch")


def test_injector_actions_map_to_exception_types():
    inj = FaultInjector("ingest:chunk:0:oom,init:connect:0:preempt")
    with pytest.raises(InjectedResourceExhausted) as ei:
        inj.hit("ingest:chunk")
    assert is_resource_exhausted(ei.value)
    with pytest.raises(SimulatedPreemption):
        inj.hit("init:connect")


def test_fault_site_inert_without_env():
    for _ in range(5):
        fault_site("sgd:epoch")  # no env -> no-op
    assert not fault_sites_active("sgd:epoch")


def test_fault_site_env_driven(monkeypatch):
    monkeypatch.setenv("TPUML_FAULT_SPEC", "sgd:epoch:1:raise")
    reset_faults()
    assert fault_sites_active("sgd:epoch")
    fault_site("sgd:epoch")
    with pytest.raises(InjectedFault):
        fault_site("sgd:epoch")
    assert not fault_sites_active("sgd:epoch")  # spent


# ---------------------------------------------------------------------------
# backoff schedule + with_retries
# ---------------------------------------------------------------------------


def test_backoff_schedule_shape_and_jitter():
    sched = backoff_schedule(6, 100.0, seed=3)
    assert len(sched) == 6
    for a, delay in enumerate(sched):
        base = min(100.0 * 2**a, 30_000.0)
        assert 0.5 * base <= delay < base  # equal jitter band
    # deterministic for a given seed
    assert sched == backoff_schedule(6, 100.0, seed=3)
    assert sched != backoff_schedule(6, 100.0, seed=4)


def test_backoff_schedule_caps_at_30s():
    sched = backoff_schedule(12, 100.0, seed=0)
    assert all(d < 30_000.0 for d in sched)
    assert sched[-1] >= 15_000.0  # capped base, >= half after jitter


def test_with_retries_inert_at_zero_budget():
    calls = []

    def fn():
        calls.append(1)
        raise RuntimeError("always")

    with pytest.raises(RuntimeError):
        with_retries(fn, what="t", retries=0)
    assert len(calls) == 1  # single attempt, no retry machinery
    assert counters.get("retries") == 0


def test_with_retries_recovers_and_counts():
    sleeps = []
    state = {"n": 0}

    def fn():
        state["n"] += 1
        if state["n"] < 3:
            raise RuntimeError(f"transient {state['n']}")
        return "ok"

    out = with_retries(
        fn, what="t", retries=5, backoff_ms=10.0, sleep=sleeps.append
    )
    assert out == "ok"
    assert state["n"] == 3
    assert len(sleeps) == 2
    assert counters.get("retries") == 2


def test_with_retries_exhausts_budget():
    def fn():
        raise RuntimeError("always")

    with pytest.raises(RuntimeError, match="always"):
        with_retries(fn, what="t", retries=2, backoff_ms=1.0, sleep=lambda s: None)
    assert counters.get("retries") == 2


def test_with_retries_never_retries_preemption():
    calls = []

    def fn():
        calls.append(1)
        raise SimulatedPreemption("pod gone")

    with pytest.raises(SimulatedPreemption):
        with_retries(fn, what="t", retries=5, backoff_ms=1.0, sleep=lambda s: None)
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# checkpointer unit behavior
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip_and_clear(tmp_path):
    ckpt = FitCheckpointer("algo", {"k": 3, "seed": 7}, str(tmp_path), every=1)
    w = np.arange(6, dtype=np.float64).reshape(2, 3)
    ckpt.save(4, {"w": w}, {"f": 1.5})
    it, arrays, extra = ckpt.load()
    assert it == 4
    np.testing.assert_array_equal(arrays["w"], w)
    assert extra["f"] == 1.5
    ckpt.clear()
    assert ckpt.load() is None
    assert list(tmp_path.iterdir()) == []


def test_checkpoint_params_hash_mismatch_cold_starts(tmp_path):
    FitCheckpointer("algo", {"k": 3}, str(tmp_path)).save(2, {"w": np.ones(2)})
    assert FitCheckpointer("algo", {"k": 4}, str(tmp_path)).load() is None
    assert FitCheckpointer("other", {"k": 3}, str(tmp_path)).load() is None
    assert FitCheckpointer("algo", {"k": 3}, str(tmp_path)).load() is not None


def test_checkpoint_corruption_cold_starts(tmp_path):
    ckpt = FitCheckpointer("algo", {"k": 3}, str(tmp_path))
    ckpt.save(1, {"w": np.ones(2)})
    npz = [p for p in tmp_path.iterdir() if p.suffix == ".npz"]
    npz[0].write_bytes(b"not an npz")
    assert ckpt.load() is None  # never raises


def test_checkpoint_maybe_save_cadence(tmp_path):
    ckpt = FitCheckpointer("algo", {}, str(tmp_path), every=3)
    for it in range(1, 7):
        ckpt.maybe_save(it, {"w": np.full(2, it)})
        expected = (it // 3) * 3
        if expected:
            assert ckpt.load()[0] == expected
        else:
            assert ckpt.load() is None


def test_checkpoint_disabled_is_noop(tmp_path):
    ckpt = FitCheckpointer.from_env("algo", {"k": 1})  # no TPUML_CKPT_DIR
    assert not ckpt.enabled
    ckpt.save(1, {"w": np.ones(2)})
    assert ckpt.load() is None
    ckpt.clear()


def test_params_hash_covers_array_digests():
    a = np.arange(8, dtype=np.float32)
    h1 = params_hash({"x": array_digest(a)})
    h2 = params_hash({"x": array_digest(a + 1)})
    assert h1 != h2
    assert array_digest(a) == array_digest(a.copy())


# ---------------------------------------------------------------------------
# chunk halving
# ---------------------------------------------------------------------------


def test_split_chunk_preserves_rows_and_validity():
    from spark_rapids_ml_tpu.data.chunks import Chunk
    from spark_rapids_ml_tpu.ops.streaming import _split_chunk

    X = np.arange(64 * 3, dtype=np.float32).reshape(64, 3)
    y = np.arange(64, dtype=np.float32)
    c = Chunk(X=X, n_valid=40, y=y)
    a, b = _split_chunk(c, row_mult=8)
    assert a.X.shape[0] % 8 == 0 and b.X.shape[0] % 8 == 0
    assert a.X.shape[0] + b.X.shape[0] == 64
    assert a.n_valid + b.n_valid == 40
    np.testing.assert_array_equal(np.concatenate([a.X, b.X]), X)
    np.testing.assert_array_equal(np.concatenate([a.y, b.y]), y)
    # unsplittable: below 2x the row multiple
    assert _split_chunk(Chunk(X=X[:8], n_valid=8), row_mult=8) is None


def test_streamed_fit_survives_injected_oom_by_halving(monkeypatch, rng):
    from spark_rapids_ml_tpu.clustering import KMeans

    X = rng.normal(size=(256, 5)).astype(np.float64)
    X[:64] += 4.0
    X[64:128] -= 4.0
    df = DataFrame({"features": X})

    def fit():
        return KMeans(
            k=4, maxIter=6, tol=1e-8, seed=5, num_workers=4,
            streaming=True, stream_chunk_rows=64,
        ).setFeaturesCol("features").fit(df)

    clean = fit()

    monkeypatch.setenv("TPUML_RETRIES", "2")
    monkeypatch.setenv("TPUML_BACKOFF_MS", "1")
    monkeypatch.setenv("TPUML_FAULT_SPEC", "ingest:chunk:1:oom")
    reset_faults()
    base = counters.snapshot()
    degraded = fit()
    delta = counters.delta_since(base)
    assert delta.get("chunk_halvings", 0) >= 1
    # a split chunk folds into the same sums (up to fp reassociation)
    np.testing.assert_allclose(
        degraded.cluster_centers_, clean.cluster_centers_, rtol=1e-4, atol=1e-5
    )


# ---------------------------------------------------------------------------
# prefetch exception propagation
# ---------------------------------------------------------------------------


def test_prefetch_propagates_worker_traceback():
    from spark_rapids_ml_tpu.ops.streaming import prefetch_chunks

    def bad_source():
        yield "c0"
        raise ValueError("boom-in-producer")

    with pytest.raises(ValueError, match="boom-in-producer") as ei:
        list(prefetch_chunks(bad_source(), depth=2))
    frames = "".join(traceback.format_tb(ei.value.__traceback__))
    assert "bad_source" in frames  # original producer frame, not a rewrap


# ---------------------------------------------------------------------------
# distributed bootstrap config validation + retry
# ---------------------------------------------------------------------------


def test_dist_env_validation(monkeypatch):
    from spark_rapids_ml_tpu.parallel.context import (
        DistConfigError,
        TpuDistContext,
        distributed_env_configured,
    )

    monkeypatch.setenv("TPUML_COORDINATOR", "127.0.0.1:9999")
    monkeypatch.setenv("TPUML_NUM_PROCS", "abc")
    with pytest.raises(DistConfigError, match="TPUML_NUM_PROCS"):
        distributed_env_configured()

    monkeypatch.setenv("TPUML_NUM_PROCS", "2")
    monkeypatch.setenv("TPUML_PROC_ID", "2")
    with pytest.raises(DistConfigError, match="TPUML_PROC_ID"):
        TpuDistContext()

    monkeypatch.setenv("TPUML_NUM_PROCS", "0")
    monkeypatch.delenv("TPUML_PROC_ID")
    with pytest.raises(DistConfigError, match="must be >= 1"):
        TpuDistContext()

    with pytest.raises(DistConfigError):
        TpuDistContext(
            coordinator="127.0.0.1:9999", num_processes=2, process_id=3
        )


def test_dist_bootstrap_retries_connect_faults(monkeypatch):
    import spark_rapids_ml_tpu.parallel.context as ctx

    monkeypatch.setenv("TPUML_COORDINATOR", "127.0.0.1:9999")
    monkeypatch.setenv("TPUML_NUM_PROCS", "2")
    monkeypatch.setenv("TPUML_PROC_ID", "0")
    monkeypatch.setenv("TPUML_RETRIES", "3")
    monkeypatch.setenv("TPUML_BACKOFF_MS", "1")
    # first two connect attempts die; the third must succeed
    monkeypatch.setenv(
        "TPUML_FAULT_SPEC", "init:connect:0:raise,init:connect:1:raise"
    )
    reset_faults()

    connects = []
    monkeypatch.setattr(
        ctx.jax.distributed, "initialize", lambda **kw: connects.append(kw)
    )
    monkeypatch.setattr(ctx, "_process_initialized", False)
    c = ctx.TpuDistContext()
    c.__enter__()
    assert len(connects) == 1  # the successful (third) attempt reached jax
    assert counters.get("retries") == 2
    monkeypatch.setattr(ctx, "_process_initialized", False)


# ---------------------------------------------------------------------------
# interrupted-then-resumed == uninterrupted (the tentpole contract)
# ---------------------------------------------------------------------------


def _ckpt_files(d):
    return sorted(os.listdir(d))


def test_kmeans_preempt_resume_same_seed_equivalent(monkeypatch, tmp_path, rng):
    from spark_rapids_ml_tpu.clustering import KMeans

    X = rng.normal(size=(256, 5)).astype(np.float64)
    X[:64] += 4.0
    X[64:128] -= 4.0
    df = DataFrame({"features": X})

    def fit():
        return KMeans(
            k=4, maxIter=8, tol=1e-12, seed=5, num_workers=4,
            streaming=True, stream_chunk_rows=64,
        ).setFeaturesCol("features").fit(df)

    clean = fit()

    monkeypatch.setenv("TPUML_CKPT_DIR", str(tmp_path))
    monkeypatch.setenv("TPUML_CKPT_EVERY", "1")
    monkeypatch.setenv("TPUML_FAULT_SPEC", "sgd:epoch:2:preempt")
    reset_faults()
    with pytest.raises(SimulatedPreemption):
        fit()
    assert _ckpt_files(tmp_path)  # snapshot committed before the fault

    monkeypatch.delenv("TPUML_FAULT_SPEC")
    reset_faults()
    base = counters.snapshot()
    resumed = fit()
    delta = counters.delta_since(base)
    assert delta.get("resumed_fits") == 1
    assert delta.get("resumed_from") == 2
    assert resumed._resilience_report.get("resumed_fits") == 1
    np.testing.assert_allclose(
        resumed.cluster_centers_, clean.cluster_centers_, rtol=0, atol=1e-12
    )
    assert resumed.trainingCost == pytest.approx(clean.trainingCost, rel=1e-12)
    assert _ckpt_files(tmp_path) == []  # cleared on success


def test_logreg_preempt_resume_same_seed_equivalent(monkeypatch, tmp_path, rng):
    from spark_rapids_ml_tpu.classification import LogisticRegression

    X = rng.normal(size=(200, 4)).astype(np.float64)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    df = DataFrame({"features": X, "label": y})

    def fit():
        return LogisticRegression(
            maxIter=15, regParam=0.01, tol=1e-12, num_workers=4,
            streaming=True, stream_chunk_rows=64,
        ).setFeaturesCol("features").fit(df)

    clean = fit()

    monkeypatch.setenv("TPUML_CKPT_DIR", str(tmp_path))
    monkeypatch.setenv("TPUML_CKPT_EVERY", "1")
    monkeypatch.setenv("TPUML_FAULT_SPEC", "sgd:epoch:3:preempt")
    reset_faults()
    with pytest.raises(SimulatedPreemption):
        fit()
    assert _ckpt_files(tmp_path)

    monkeypatch.delenv("TPUML_FAULT_SPEC")
    reset_faults()
    base = counters.snapshot()
    resumed = fit()
    delta = counters.delta_since(base)
    assert delta.get("resumed_fits") == 1
    assert delta.get("resumed_from") == 3
    # the restored f64 carry (w/f/g/S/Y) makes the resumed walk identical
    np.testing.assert_allclose(
        resumed.coefficients, clean.coefficients, rtol=0, atol=1e-12
    )
    np.testing.assert_allclose(
        resumed.intercept, clean.intercept, rtol=0, atol=1e-12
    )
    assert _ckpt_files(tmp_path) == []


def test_umap_preempt_resume_same_seed_equivalent(monkeypatch, tmp_path, rng):
    from spark_rapids_ml_tpu.umap import UMAP

    X = rng.normal(size=(60, 6)).astype(np.float32)
    df = DataFrame({"features": X})

    def fit():
        return UMAP(
            n_neighbors=8, random_state=3, init="random", n_epochs=20,
            num_workers=1,
        ).setFeaturesCol("features").fit(df)

    clean = fit()

    monkeypatch.setenv("TPUML_CKPT_DIR", str(tmp_path))
    monkeypatch.setenv("TPUML_CKPT_EVERY", "5")
    # segment boundaries are the fault sites: index 2 -> epoch 10
    monkeypatch.setenv("TPUML_FAULT_SPEC", "sgd:epoch:2:preempt")
    reset_faults()
    with pytest.raises(SimulatedPreemption):
        fit()
    assert _ckpt_files(tmp_path)

    monkeypatch.delenv("TPUML_FAULT_SPEC")
    reset_faults()
    base = counters.snapshot()
    resumed = fit()
    delta = counters.delta_since(base)
    assert delta.get("resumed_fits") == 1
    assert delta.get("resumed_from") == 10
    # absolute-epoch RNG/alpha: segmented+resumed == single fused loop
    np.testing.assert_allclose(
        resumed.embedding_, clean.embedding_, rtol=1e-5, atol=1e-5
    )
    assert _ckpt_files(tmp_path) == []


def test_umap_engine_segmented_epochs_match_fused(rng):
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.umap_kernels import optimize_embedding_rows

    n, c, R, K = 32, 2, 32, 4
    emb0 = jnp.asarray(rng.normal(size=(n, c)).astype(np.float32))
    row_heads = jnp.asarray(np.sort(rng.integers(0, n, size=R)).astype(np.int32))
    tails = jnp.asarray(rng.integers(0, n, size=(R, K)).astype(np.int32))
    p = jnp.asarray(rng.uniform(0.2, 1.0, size=(R, K)).astype(np.float32))
    key = jax.random.PRNGKey(11)
    kwargs = dict(n_epochs=9, a=1.6, b=0.9, negative_sample_rate=3)

    fused = optimize_embedding_rows(emb0, emb0, row_heads, tails, p, key, **kwargs)
    emb = emb0
    for e0, span in ((0, 4), (4, 4), (8, 1)):
        emb = optimize_embedding_rows(
            emb, emb, row_heads, tails, p, key,
            epoch_offset=e0, epoch_span=span, **kwargs,
        )
    np.testing.assert_allclose(
        np.asarray(emb), np.asarray(fused), rtol=1e-6, atol=1e-6
    )


# ---------------------------------------------------------------------------
# CrossValidator graceful degradation
# ---------------------------------------------------------------------------


def _cv_setup():
    from spark_rapids_ml_tpu.evaluation import RegressionEvaluator
    from spark_rapids_ml_tpu.regression import LinearRegression
    from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

    class FlakyLR(LinearRegression):
        POISON = 12345.0

        def _supportsTransformEvaluate(self, eva):
            return False  # exercise the per-param-map loop

        def fit(self, dataset, params=None):
            if params and any(v == self.POISON for v in params.values()):
                raise RuntimeError("injected fit failure (poison combo)")
            return super().fit(dataset, params)

    rng = np.random.default_rng(8)
    X = rng.normal(size=(240, 5))
    w = rng.normal(size=5)
    y = X @ w + 0.1 * rng.normal(size=240)
    df = DataFrame({"features": X, "label": y})
    est = FlakyLR(float32_inputs=False).setFeaturesCol("features")
    grid = (
        ParamGridBuilder()
        .addGrid(est.getParam("regParam"), [0.0, 0.01, FlakyLR.POISON])
        .build()
    )
    cv = CrossValidator(
        estimator=est,
        estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(metricName="rmse"),
        numFolds=3,
        seed=1,
    )
    return cv, df


def test_cv_default_is_failfast():
    cv, df = _cv_setup()
    with pytest.raises(RuntimeError, match="poison"):
        cv.fit(df)


def test_cv_tolerant_mode_records_worst_metric(monkeypatch):
    monkeypatch.setenv("TPUML_CV_FAILFAST", "0")
    cv, df = _cv_setup()
    base = counters.snapshot()
    model = cv.fit(df)
    delta = counters.delta_since(base)
    assert delta.get("cv_failed_fits") == 3  # poison combo x 3 folds
    # rmse: smaller is better -> failed combo recorded as +inf, never wins
    assert model.avgMetrics[2] == np.inf
    assert np.isfinite(model.avgMetrics[0]) and np.isfinite(model.avgMetrics[1])
    from spark_rapids_ml_tpu.evaluation import RegressionEvaluator

    assert RegressionEvaluator(metricName="r2").evaluate(model.transform(df)) > 0.9


# ---------------------------------------------------------------------------
# inertness: no resilience env -> zero behavior change
# ---------------------------------------------------------------------------


def test_clean_path_is_fully_inert(rng):
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.runtime.retry import resolve_retries

    assert resolve_retries() == 0
    X = rng.normal(size=(192, 4)).astype(np.float64)
    df = DataFrame({"features": X})
    base = counters.snapshot()
    model = (
        KMeans(k=3, maxIter=5, seed=2, num_workers=4,
               streaming=True, stream_chunk_rows=64)
        .setFeaturesCol("features")
        .fit(df)
    )
    assert counters.delta_since(base) == {}
    assert model._resilience_report == {}
