"""In-situ attribution of RF build costs: time FULL 13-level tree builds
with individual stages knocked out (semantically wrong, cost-indicative).

Variants:
  full      — unmodified _build_tree
  nofeats   — per-node subsets replaced by one fixed subset (skips top_k)
  noroute   — rows never move (skips routing gathers + child update)
  nogain    — split search replaced by slot-0/bin-median constants
  nosubset  — histogram fed bins[:, :16] directly (skips contract gather)
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.ops import tree_kernels as tk
from spark_rapids_ml_tpu.ops.rf_pallas import BLOCK_ROWS

N = 131072
D = 256
K = 16
NB = 128
S = 2
DEPTH = 13


def build_tree_variant(bins, stats, valid, key, cfg, *, knock=None):
    n, d_pad = bins.shape
    S = cfg.n_stats
    nb = cfg.n_bins
    M = tk.max_nodes(cfg.max_depth)
    dt = stats.dtype
    kb, kf = jax.random.split(jnp.asarray(key))
    w = valid.astype(dt)
    sw = stats * w[:, None]
    feat = jnp.full((M,), -1, jnp.int32)
    thr_bin = jnp.zeros((M,), jnp.int32)
    leaf = jnp.zeros((M, S), dt)
    node = jnp.zeros((n,), jnp.int32)
    packed = tk._pack_bins(bins)

    for level in range(cfg.max_depth + 1):
        offset = (1 << level) - 1
        n_nodes = 1 << level
        local = node - offset
        in_level = (local >= 0) & (local < n_nodes)
        seg = jnp.where(in_level, local, n_nodes).astype(jnp.int32)
        if level == cfg.max_depth:
            parent = jax.ops.segment_sum(sw, seg, num_segments=n_nodes + 1)[:n_nodes]
            leaf = leaf.at[offset:offset + n_nodes].set(parent)
            break

        if knock == "nofeats":
            base = jnp.arange(K, dtype=jnp.int32) * (D // K)
            feats = jnp.broadcast_to(base[None, :], (n_nodes, K))
        else:
            r = jax.random.uniform(
                jax.random.fold_in(kf, level), (n_nodes, cfg.n_features))
            feats = lax.top_k(r, cfg.k_features)[1].astype(jnp.int32)

        lc0 = jnp.clip(local, 0, n_nodes - 1)
        if knock == "nosubset":
            hist_src = bins[:, :K].astype(jnp.int32)
        else:
            row_feats = feats[lc0]
            hist_src = tk._contract_gather(packed, row_feats)

        r_sub = tk._compact_r_sub(n, n_nodes, BLOCK_ROWS, S)
        n_pad_c = -(-(n + (n_nodes + 1) * r_sub) // BLOCK_ROWS) * BLOCK_ROWS
        hist_full, parent = tk._hist_compact(
            hist_src, seg, sw, n_nodes=n_nodes, nb=nb, r_sub=r_sub,
            n_pad=n_pad_c, f_chunk=K, variance=False)
        leaf = leaf.at[offset:offset + n_nodes].set(parent)
        pcount = tk._count(parent, cfg.impurity)
        pimp = tk._impurity(parent, cfg.impurity)

        if knock == "nogain":
            bg = jnp.ones((n_nodes,), dt) + hist_full.sum() * 1e-30
            bf = jnp.broadcast_to(jnp.int32(0), (n_nodes,))
            bb = jnp.full((n_nodes,), NB // 2, jnp.int32)
        else:
            g, f, b = tk._best_splits_from_hist(
                hist_full, parent, pcount, pimp, feats.T, nb, cfg)
            bg, bf, bb = g, f, b

        do_split = jnp.isfinite(bg) & (bg >= 1e-9) & (pcount >= cfg.min_samples_split)
        feat = feat.at[offset:offset + n_nodes].set(jnp.where(do_split, bf, -1))
        thr_bin = thr_bin.at[offset:offset + n_nodes].set(bb)

        if knock == "noroute":
            # rows stay at node 0's subtree spine: wrong but cheap
            node = jnp.where(in_level, 2 * node + 1 + (bb[lc0] // NB), node)
        else:
            row_feat = bf[lc0]
            row_bin = tk._contract_gather(packed, row_feat[:, None])[:, 0]
            go_right = (row_bin > bb[lc0]).astype(jnp.int32)
            child = 2 * node + 1 + go_right
            moves = in_level & do_split[lc0]
            node = jnp.where(moves, child, node)

    return {"feature": feat, "threshold_bin": thr_bin, "leaf_stats": leaf}


def main():
    rng = np.random.default_rng(0)
    bins = jnp.asarray(rng.integers(0, NB, size=(N, D), dtype=np.uint8))
    yc = rng.integers(0, 2, size=N)
    stats = jnp.asarray(np.eye(2, dtype=np.float32)[yc])
    valid = jnp.ones((N,), jnp.float32)
    cfg = tk.ForestConfig(
        max_depth=DEPTH, n_bins=NB, n_features=D, n_stats=S, impurity="gini",
        k_features=K, min_samples_leaf=1, min_info_gain=0.0,
        min_samples_split=2, bootstrap=False)

    # pre-staged perturbed copies: a per-rep host->device push of 33 MB
    # costs ~0.5 s through the tunnel and would swamp the build time
    bins_reps = [
        jax.block_until_ready(
            jnp.asarray((np.asarray(bins) + (r + 1)) % NB, jnp.uint8))
        for r in range(3)
    ]
    for knock in [None, "nofeats", "nosubset", "nogain", "noroute"]:
        # each knockout variant IS a distinct program; compiled once per
        # variant and reused across the timed reps  # tpuml: ignore[TPU003]
        fn = jax.jit(lambda b, st, v, k, kn=knock: build_tree_variant(
            b, st, v, k, cfg, knock=kn))
        # fixed key on purpose: all variants must see identical splits
        # tpuml: ignore[TPU004]
        out = fn(bins, stats, valid, jax.random.PRNGKey(1))
        jax.block_until_ready(out)
        best = 1e30
        for r in range(3):
            t0 = time.perf_counter()
            # same fixed key as the warm call  # tpuml: ignore[TPU004]
            out = fn(bins_reps[r], stats, valid, jax.random.PRNGKey(1))
            np.asarray(out["feature"])
            best = min(best, time.perf_counter() - t0)
        print(f"{str(knock):10s}: {best*1e3:7.1f} ms/tree")


if __name__ == "__main__":
    main()
