from .dataframe import DataFrame, Row, kfold

__all__ = ["DataFrame", "Row", "kfold"]
