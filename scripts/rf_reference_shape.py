"""RandomForest at the reference's FULL benchmark shape on one chip.

The reference runs RandomForestClassifier(numTrees=50, maxDepth=13,
maxBins=128) on 1M x 3000 on a 2x A10G cluster inside a 3600 s budget
(``/root/reference/python/benchmark/databricks/run_benchmark.sh:102-112``),
with featureSubsetStrategy at Spark's default "auto" -> sqrt(3000) = 55
features per split (``tree.py:380-386``). Before the subset-exploiting
histogram path (``ops/tree_kernels.py``), the all-features cost model put
this config at ~1-2 h per chip; with n*k*S updates it drops to minutes.

Memory design for one 16 GB v5e: the f32 design matrix (12 GB) never
materializes — rows are generated on device in chunks, binized to uint8
immediately, and only the (n, d_pad) binned matrix (~4 GB) plus labels
are kept.

Usage: python scripts/rf_reference_shape.py [--rows N] [--cols D]
       [--trees T] [--depth L] [--group G]
Prints one JSON line with wall-clock and config.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from spark_rapids_ml_tpu.utils.platform import pin_platform  # noqa: E402

pin_platform(os.environ.get("RFDEMO_PLATFORM"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--cols", type=int, default=3000)
    ap.add_argument("--trees", type=int, default=50)
    ap.add_argument("--depth", type=int, default=13)
    ap.add_argument("--bins", type=int, default=128)
    # trees per dispatch: a multi-minute single device program outlives
    # remote-runtime health checks (round-2 postmortem)
    ap.add_argument("--group", type=int, default=4)
    args = ap.parse_args()

    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.models.tree import _resolve_k_features
    from spark_rapids_ml_tpu.ops.tree_kernels import (
        ForestConfig,
        build_forest,
        next_pow2,
        resolve_contract_gather,
        resolve_hist_strategy,
    )
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    n, d, nb = args.rows, args.cols, args.bins
    # lane-aligned (not pow2) feature padding: the compact+subset build
    # path only needs d_pad % 4 == 0 (word packing) and clipping room for
    # take_along_axis; 3000 -> 3072 instead of 4096 keeps the resident
    # binned matrix at 3.2 GB instead of 4.3 GB — the tunnel chip exposes
    # only ~8 GB HBM (probed round 4), and the pow2 pad OOMed the fit
    d_pad = -(-d // 256) * 256
    k = _resolve_k_features("auto", d, True)
    mesh = make_mesh(len(jax.devices()))
    n_dp = mesh.shape["dp"]
    sh = NamedSharding(mesh, P("dp"))

    # small generation chunks: the (chunk, 3000) f32 block plus the i32
    # searchsorted output are ~800 MB at 16k rows — transients must fit
    # beside the 3.2 GB binned matrix in ~8 GB visible HBM
    rows_per_chunk = 16_384
    gchunk = rows_per_chunk * n_dp
    n_pad = ((n + gchunk - 1) // gchunk) * gchunk
    w_true = jnp.asarray(
        np.random.default_rng(0).standard_normal(d, dtype=np.float32)
    )
    # data is synthetic i.i.d. N(0,1), so the exact standard-normal
    # quantiles serve as bin edges for every feature (the estimator path
    # sketches per-feature sample quantiles instead)
    from jax.scipy.special import ndtri

    edges = jnp.asarray(
        ndtri(np.linspace(0.0, 1.0, nb + 1)[1:-1]), jnp.float32
    )

    t0 = time.perf_counter()

    # Chunked generate -> binize -> place, as SEPARATE small programs
    # with a DONATED placement buffer. A single fori-loop program holds
    # the (n_pad, d_pad) carry double-buffered — at 1M x 3072 that is
    # 2 x 3.1 GB the tunnel backend then keeps resident into the fit,
    # which OOMed the ~8 GB visible HBM (round-4 bisection; each stage
    # runs alone, gen-then-fit faulted). Donation keeps the peak at one
    # binned matrix + one 16k-row piece. NOTE: every device array the
    # jits touch rides as an ARGUMENT — a jit-captured device constant
    # (the original `edges` closure) deterministically faulted this
    # backend.
    import functools

    def _piece(key, i, w, edges):
        blk = jax.random.normal(
            jax.random.fold_in(key, i), (gchunk, d), jnp.float32
        )
        y = (blk @ w > 0).astype(jnp.float32)
        b = jnp.searchsorted(edges, blk, side="right").astype(jnp.uint8)
        b = jnp.pad(b, ((0, 0), (0, d_pad - d)))
        return b, jnp.stack([1.0 - y, y], axis=1)

    gen_piece = jax.jit(_piece, out_shardings=(sh, sh))

    @functools.partial(jax.jit, donate_argnums=(0,), out_shardings=sh)
    def place(ba, piece, i):
        return lax.dynamic_update_slice_in_dim(ba, piece, i * gchunk, 0)

    zeros_u8 = jax.jit(
        lambda: jnp.zeros((n_pad, d_pad), jnp.uint8), out_shardings=sh
    )
    zeros_f32 = jax.jit(
        lambda: jnp.zeros((n_pad, 2), jnp.float32), out_shardings=sh
    )
    bins, stats = zeros_u8(), zeros_f32()
    key0 = jax.random.key(11)
    for i in range(n_pad // gchunk):
        b, st = gen_piece(key0, jnp.int32(i), w_true, edges)
        bins = place(bins, b, jnp.int32(i))
        stats = place(stats, st, jnp.int32(i))
    mask_fn = jax.jit(
        lambda: (jnp.arange(n_pad) < n).astype(jnp.float32), out_shardings=sh
    )
    mask = mask_fn()
    # one-shot setup, runs once per demo invocation  # tpuml: ignore[TPU003]
    stats = jax.jit(
        lambda s, m: s * m[:, None], donate_argnums=(0,), out_shardings=sh
    )(stats, mask)
    jax.block_until_ready(bins)
    t_gen = time.perf_counter() - t0
    print(f"[rf-demo] binned data ready in {t_gen:.1f}s "
          f"({n}x{d} -> uint8 {n_pad}x{d_pad})", file=sys.stderr)

    cfg = ForestConfig(
        max_depth=args.depth, n_bins=nb, n_features=d, n_stats=2,
        impurity="gini", k_features=k, min_samples_leaf=1,
        min_info_gain=0.0, min_samples_split=2, bootstrap=True,
        hist_strategy=resolve_hist_strategy(),
        contract_gather=resolve_contract_gather(),
    )
    trees_per_dev = -(-args.trees // n_dp)
    group = min(args.group, trees_per_dev)
    trees_per_dev = -(-trees_per_dev // group) * group
    keys = jax.random.key_data(
        jax.random.split(jax.random.key(5), n_dp * trees_per_dev)
    ).reshape(n_dp, trees_per_dev, 2)
    keys = jax.device_put(np.asarray(keys), sh)

    fit = jax.jit(
        lambda b, m, s, kg: build_forest(b, m, s, kg, mesh=mesh, cfg=cfg)
    )
    t1 = time.perf_counter()
    depths = []
    for gi, g0 in enumerate(range(0, trees_per_dev, group)):
        out = fit(bins, mask, stats, keys[:, g0 : g0 + group])
        feat = np.asarray(out["feature"])  # (n_dp*group, M) fetch = sync
        depths.append(int((feat >= 0).sum()))
        print(
            f"[rf-demo] group {gi}: trees {g0}..{g0 + group - 1} done, "
            f"{time.perf_counter() - t1:.1f}s elapsed, "
            f"splits so far {sum(depths)}",
            file=sys.stderr,
        )
    t_fit = time.perf_counter() - t1
    n_trees = trees_per_dev * n_dp

    print(json.dumps({
        "metric": "rf_reference_shape_fit",
        "rows": n, "cols": d, "trees": n_trees, "max_depth": args.depth,
        "n_bins": nb, "k_features": k,
        "gen_binize_seconds": round(t_gen, 1),
        "fit_seconds": round(t_fit, 1),
        "seconds_per_tree": round(t_fit / n_trees, 2),
        "total_splits": sum(depths),
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
        "n_chips": n_dp,
        "reference_envelope_seconds": 3600,
        "reference_hardware": "2x A10G",
    }))


if __name__ == "__main__":
    main()
