"""Device-resident model registry for the serving runtime.

Loads persisted models (or adopts already-fitted ones) and pins their
transform state on the device: the packed forest tables, PCA projection
and linear/logistic coefficient matrices, and the UMAP training table +
memoized IVF transform index all get hoisted exactly once, so a request
never pays a per-call rebuild. Residency is accounted against
``TPUML_SERVE_HBM_BUDGET`` with least-recently-used eviction, and the
running total is filed under the ``serve_registry`` site of the
``hbm_budget_bytes``/``hbm_live_bytes`` gauges.

Warmup: every padded bucket shape of a coalescable model's transform
program is compiled at load (``TPUML_SERVE_WARMUP``), under a
per-(model, bucket) span name — so in steady state the dispatch span
sees zero XLA compiles and the retrace watchdog's ``retrace_storms``
counter stays at 0 (the serving contract).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ..runtime import envspec, faults, lockwitness, opsplane, telemetry

_LOGGER = logging.getLogger("spark_rapids_ml_tpu.serving")


class ModelReloadError(RuntimeError):
    """A registered model's recorded load path is gone: the transparent
    reload of an evicted entry (or an explicit :meth:`ModelRegistry.load`)
    found no persisted model at the path. Typed so callers see a serving
    error naming the model, not a ``FileNotFoundError`` from deep inside
    persistence."""


class SwapError(RuntimeError):
    """A hot-swap failed before completing. ``stage`` names where it
    died (``load``/``warm``/``flip``); whatever the stage, the prior
    version is untouched and still serving — the new entry is only
    routed to by the final atomic flip."""

    def __init__(self, message: str, stage: str = "swap") -> None:
        super().__init__(message)
        self.stage = stage

# floor of the padded bucket ladder; requests below it pad up to 8 rows
# (except single-row requests, dispatched exact — see docs/serving.md
# on the XLA n=1 gemv specialization)
MIN_BUCKET_ROWS = 8


# ---------------------------------------------------------------------------
# per-family serving policy
# ---------------------------------------------------------------------------


def serving_family(model: Any) -> str:
    """Family tag deciding the fast path: ``rf``/``gbt`` pin their own
    resolved traversal engine, ``umap`` rides the memoized IVF index but is
    never coalesced (its refine RNG draws negative-sample offsets from
    ``[0, n_rows)`` — any row-count change perturbs every row), the
    dense linear families coalesce freely, and unknown models fall back
    to ``generic`` (exact-shape dispatch, no padding)."""
    from ..models.feature import PCAModel
    from ..models.regression import LinearRegressionModel
    from ..models.classification import LogisticRegressionModel
    from ..models.tree import _ForestModelBase, _GBTModel
    from ..models.umap import UMAPModel

    if isinstance(model, _GBTModel):
        return "gbt"
    if isinstance(model, _ForestModelBase):
        return "rf"
    if isinstance(model, PCAModel):
        return "pca"
    if isinstance(model, LinearRegressionModel):
        return "linreg"
    if isinstance(model, LogisticRegressionModel):
        return "logreg"
    if isinstance(model, UMAPModel):
        return "umap"
    return "generic"


# families ELIGIBLE for padded micro-batching (row-independent
# transforms). Eligibility is necessary, not sufficient: registration
# runs an empirical pad-invariance probe per model, because whether a
# backend's kernels are bitwise row-stable is a lowering property, not
# an algebraic one — e.g. XLA CPU's mat-vec (1-D coefficients, k=1
# gemm) picks an n-dependent reduction strategy, while its k>=3 gemms
# and the tree gather engines are exactly row-stable. umap is NEVER
# eligible: its refine couples every output to the batch row count.
_COALESCE_FAMILIES = ("rf", "gbt", "pca", "linreg", "logreg")


def feature_width(model: Any) -> int:
    """Input feature dimension, family-agnostically (warmup needs it to
    synthesize bucket-shaped probe batches)."""
    for probe in (
        lambda m: int(m.numFeatures),
        lambda m: int(np.asarray(m.components_).shape[1]),
        lambda m: int(np.atleast_2d(np.asarray(m.coefficients)).shape[-1]),
        lambda m: int(np.atleast_2d(np.asarray(m.coef_)).shape[-1]),
        lambda m: int(np.asarray(m.raw_data_).shape[1]),
    ):
        try:
            return probe(model)
        except Exception:
            continue
    raise ValueError(
        f"cannot infer feature width of {type(model).__name__}; "
        "register with an explicit warmup=False"
    )


def _array_bytes(obj: Any, seen: Optional[Set[int]] = None) -> int:
    """Recursive nbytes of every array reachable from ``obj`` (dicts,
    sequences, namedtuples/dataclasses) — the IVF index and packed
    forest live in small container objects, not bare arrays."""
    if seen is None:
        seen = set()
    if id(obj) in seen:
        return 0
    seen.add(id(obj))
    if hasattr(obj, "nbytes") and hasattr(obj, "dtype"):
        try:
            return int(obj.nbytes)
        except Exception:
            return 0
    if isinstance(obj, dict):
        return sum(_array_bytes(v, seen) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_array_bytes(v, seen) for v in obj)
    d = getattr(obj, "__dict__", None)
    if d:
        return sum(_array_bytes(v, seen) for v in d.values())
    f = getattr(obj, "_fields", None)  # namedtuple containers
    if f:
        return sum(_array_bytes(getattr(obj, n), seen) for n in f)
    return 0


def resident_nbytes(model: Any) -> int:
    """Device-resident footprint estimate of a registered model: every
    persisted array attribute (the transform closures hoist exactly
    these) plus any memoized transform index already built."""
    total = 0
    for v in model._get_model_attributes().values():
        a = np.asarray(v) if not hasattr(v, "nbytes") else v
        try:
            if getattr(a, "dtype", None) is not None and a.dtype != object:
                total += int(a.nbytes)
        except Exception:
            continue
    total += _array_bytes(getattr(model, "_ivf_index_cache", None) or {})
    return total


# ---------------------------------------------------------------------------
# resident entries
# ---------------------------------------------------------------------------


@dataclass
class ResidentModel:
    """One registered model with its resolved fast path."""

    name: str
    model: Any
    family: str
    fn: Callable[[np.ndarray], Dict[str, np.ndarray]]
    engine: str            # resolved transform engine ("packed", "xla", ...)
    coalesce: bool         # pad-invariance probe passed at registration
    nbytes: int
    n_features: int
    # monotone per-name version: bumped by every register/swap of the
    # same name (the counter survives eviction), so the lifecycle layer
    # can tell vN from vN+1 and /statusz can report what serves
    version: int = 1
    # (bucket_rows) shapes whose programs have compiled — first dispatch
    # at a cold bucket runs under a warmup span so its compiles never
    # land on the steady-state dispatch site
    warmed: Set[int] = field(default_factory=set)
    # model-axis sharding (TPUML_MESH_MP, PR 16): each of mp ranks holds
    # ceil(nbytes / mp) resident bytes — what this replica's rank
    # charges against its HBM budget (== nbytes when mp == 1)
    mp_degree: int = 1
    shard_nbytes: int = 0

    def __post_init__(self) -> None:
        if not self.shard_nbytes:
            self.shard_nbytes = -(-self.nbytes // max(1, self.mp_degree))


# the probe samples (n, bucket) pairs up to this bucket size; kernels
# whose lowering switches reduction strategy with row count (the only
# instability class observed) switch well below it
_PROBE_BUCKET_CAP = 128


def _probe_pad_invariance(
    name: str, fn: Callable, n_features: int, ladder: List[int],
    rank_tag: str = "",
) -> bool:
    """Empirically verify the bit-identity contract padding relies on:
    a row's outputs must not depend on batch row count, pad tail, or
    row offset.

    Two checks, all comparisons bit-for-bit against a direct exact-shape
    evaluation of the same rows: (1) offset invariance — two requests
    concatenated at the ladder floor and padded to the next bucket must
    reproduce both requests at their offsets; (2) one worst-fill odd
    size per ladder bucket (``b//2 + 1`` rows padded to ``b``) — kernel
    strategy switches are row-count-dependent, so a single small shape
    passing proves nothing about larger buckets. Any mismatch disables
    coalescing for this model (it still serves, at exact shapes).

    Runs under a warmup span so probe compiles never score as retrace
    storms. A sampled screen, not a proof — but a strategy-switching
    kernel fails one of the sampled pairs in practice, and the serving
    tests sweep sizes inside the probed envelope."""
    rng = np.random.default_rng(0)

    def run(X: np.ndarray) -> Dict[str, np.ndarray]:
        with telemetry.span(
            f"serve.warmup.{name}.probe{rank_tag}", warmup=True
        ):
            return {k: np.asarray(v) for k, v in fn(X).items()}

    a, b = 5, 3
    A = rng.standard_normal((a, n_features)).astype(np.float32)
    B = rng.standard_normal((b, n_features)).astype(np.float32)
    ref_a, ref_b = run(A), run(B)
    cat = np.concatenate([A, B], axis=0)  # == MIN_BUCKET_ROWS rows
    pad = np.concatenate(
        [cat, np.repeat(cat[:1], MIN_BUCKET_ROWS, axis=0)], axis=0
    )
    for out in (run(cat), run(pad)):
        for k, v in ref_a.items():
            if not np.array_equal(v, out[k][:a]):
                return False
        for k, v in ref_b.items():
            if not np.array_equal(v, out[k][a:a + b]):
                return False
    for bucket in ladder:
        if bucket > _PROBE_BUCKET_CAP:
            break
        n = bucket // 2 + 1
        X = rng.standard_normal((n, n_features)).astype(np.float32)
        ref = run(X)
        padded = run(
            np.concatenate([X, np.repeat(X[:1], bucket - n, axis=0)], axis=0)
        )
        for k, v in ref.items():
            if not np.array_equal(v, padded[k][:n]):
                return False
    return True


def _resolve_fast_path(model: Any, family: str) -> Tuple[Callable, str]:
    """The model's transform closure with per-call state pre-resolved.

    rf/GBT: resolve through the model's OWN engine chain (packed > bins
    > legacy under `TPUML_RF_APPLY`, same gate as a direct
    `model.transform`). Serving must not pin a different engine than
    the batch path: the packed and legacy descents disagree by one f32
    ulp in vote normalization on some inputs, and the serving contract
    is bit-identity with direct transform — which only reduces to the
    probe-verified pad-invariance property when both paths run the same
    compiled closure. On TPU the auto gate already prefers packed, so
    nothing is lost where the lockstep kernel matters. Everything else:
    the model's own memoized closure."""
    if family in ("rf", "gbt"):
        engine = model._resolve_transform_engine()
        return model._get_tpu_transform_func(engine=engine), engine
    return model._get_tpu_transform_func(), "xla"


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


class ModelRegistry:
    """LRU registry of device-resident models, packed against an HBM
    budget. Thread-safe; the serving dispatcher and concurrent loaders
    share one instance."""

    def __init__(
        self,
        hbm_budget_bytes: Optional[float] = None,
        warmup: Optional[bool] = None,
        max_bucket_rows: Optional[int] = None,
        rank: Optional[int] = None,
        mesh_mp: Optional[int] = None,
    ) -> None:
        if hbm_budget_bytes is None:
            hbm_budget_bytes = envspec.get("TPUML_SERVE_HBM_BUDGET")
        self._budget = float(hbm_budget_bytes) if hbm_budget_bytes else None
        self._warmup = (
            bool(envspec.get("TPUML_SERVE_WARMUP")) if warmup is None
            else bool(warmup)
        )
        # replica identity (pod-scale serving): rank-stamps every warmup
        # and probe span so a merged fleet trace attributes compiles to
        # the replica that paid them; None (the default) keeps all span
        # names byte-identical to single-replica serving
        self._rank = None if rank is None else int(rank)
        self._rank_tag = "" if rank is None else f".r{int(rank)}"
        # model-axis degree for residency accounting: each of mp ranks
        # holds 1/mp of a sharded model's state (PR-16 col/block
        # layouts), so the per-rank HBM budget is charged shard bytes,
        # not whole-model bytes. None = resolve TPUML_MESH_MP per model.
        self._mesh_mp = None if mesh_mp is None else max(1, int(mesh_mp))
        raw = (
            int(envspec.get("TPUML_SERVE_MAX_BUCKET_ROWS"))
            if max_bucket_rows is None else int(max_bucket_rows)
        )
        # round down to a power of two so the ladder is exactly the
        # pow2 range [MIN_BUCKET_ROWS, max]
        self._max_bucket = max(MIN_BUCKET_ROWS, 1 << (raw.bit_length() - 1))
        self._lock = lockwitness.make_rlock("registry.models")
        self._entries: "OrderedDict[str, ResidentModel]" = OrderedDict()
        self._paths: Dict[str, str] = {}
        # last version ever assigned per name — survives eviction so a
        # reload or re-register continues the sequence instead of
        # restarting at 1
        self._versions: Dict[str, int] = {}
        # name -> stage ("load"/"warm"/"flip") while a hot-swap is
        # staging; /readyz reports 503 swap_in_progress off this map
        self._swapping: Dict[str, str] = {}
        self._evictions = 0
        # weakref-tracked by the ops plane so /readyz and /statusz can
        # introspect warmup state; pure bookkeeping, starts nothing
        opsplane.track_registry(self)

    # -- introspection -----------------------------------------------------
    @property
    def max_bucket_rows(self) -> int:
        return self._max_bucket

    def bucket_ladder(self) -> List[int]:
        out, b = [], MIN_BUCKET_ROWS
        while b <= self._max_bucket:
            out.append(b)
            b <<= 1
        return out

    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    @property
    def rank(self) -> Optional[int]:
        return self._rank

    def resident_bytes(self) -> int:
        """This rank's resident bytes (shard bytes under model-axis
        sharding; whole-model bytes at mp=1)."""
        with self._lock:
            return sum(e.shard_nbytes for e in self._entries.values())

    def warmup_state(self) -> Dict[str, Any]:
        """Readiness introspection for the ops plane (`/readyz` and
        `/statusz`): per resident model, which ladder buckets are
        warmed vs pending. ``ready`` is True when every coalescable
        resident has its full bucket ladder compiled — regardless of
        whether warmup ran eagerly at register time or lazily on first
        dispatch, so readiness flips exactly when cold-bucket compiles
        can no longer stall a request."""
        ladder = self.bucket_ladder()
        with self._lock:
            models: Dict[str, Any] = {}
            ready = True
            for name, e in self._entries.items():
                pending = (
                    [b for b in ladder if b not in e.warmed]
                    if e.coalesce else []
                )
                if pending:
                    ready = False
                models[name] = {
                    "coalesce": e.coalesce,
                    "version": e.version,
                    "resident_bytes": e.nbytes,
                    "mp_degree": e.mp_degree,
                    "shard_bytes": e.shard_nbytes,
                    "warmed_buckets": sorted(e.warmed),
                    "pending_buckets": pending,
                }
            return {
                "ready": ready,
                "rank": self._rank,
                "ladder": ladder,
                "resident_bytes_total": sum(
                    e.shard_nbytes for e in self._entries.values()
                ),
                "evictions": self._evictions,
                "swaps_in_progress": dict(self._swapping),
                "models": models,
            }

    def swaps_in_progress(self) -> Dict[str, str]:
        """Names with a hot-swap staging right now, mapped to the stage
        the swap is in (``load``/``warm``/``flip``). Non-empty means the
        process should report not-ready: a kill during the window would
        strand the staged version's warmup investment (never the live
        version — that flips only at the end)."""
        with self._lock:
            return dict(self._swapping)

    @property
    def evictions(self) -> int:
        return self._evictions

    # -- load / register ---------------------------------------------------
    def _read_model(self, name: str, path: str) -> Any:
        """Load a persisted model, verifying the directory first so a
        dangling path surfaces as a typed :class:`ModelReloadError`
        naming the model, not a ``FileNotFoundError`` from persistence."""
        from ..core import _TpuModel

        if not os.path.isfile(os.path.join(path, "metadata.json")):
            raise ModelReloadError(
                f"model {name!r} cannot load from {path!r}: no persisted "
                "model there (missing metadata.json) — the recorded load "
                "path is gone or was never a model directory"
            )
        return _TpuModel.read().load(path)

    def load(self, name: str, path: str) -> ResidentModel:
        """Load a persisted model directory (any ``_TpuModel`` subclass;
        the class resolves from its metadata) and make it resident."""
        model = self._read_model(name, path)
        entry = self.register(name, model)
        with self._lock:
            self._paths[name] = path
        return entry

    def _build_entry(self, name: str, model: Any, version: int) -> ResidentModel:
        """Resolve a model's fast path into a :class:`ResidentModel`
        WITHOUT inserting it: probe pad-invariance, size residency.
        Shared by :meth:`register` (insert immediately) and :meth:`swap`
        (stage beside the live version, flip later)."""
        family = serving_family(model)
        fn, engine = _resolve_fast_path(model, family)
        n_features = feature_width(model)
        coalesce = family in _COALESCE_FAMILIES
        if coalesce:
            coalesce = _probe_pad_invariance(
                name, fn, n_features, self.bucket_ladder(),
                rank_tag=self._rank_tag,
            )
            if not coalesce:
                _LOGGER.info(
                    "serving: %s failed the pad-invariance probe on this "
                    "backend (row-count-dependent kernel lowering); it "
                    "will serve exact request shapes",
                    name,
                )
        nbytes = resident_nbytes(model)
        entry = ResidentModel(
            name=name,
            model=model,
            family=family,
            fn=fn,
            engine=engine,
            coalesce=coalesce,
            nbytes=nbytes,
            n_features=n_features,
            version=version,
            mp_degree=self._resolve_mp(nbytes),
        )
        if self._budget is not None and entry.shard_nbytes > self._budget:
            raise ValueError(
                f"model {name!r} needs {entry.shard_nbytes} resident "
                f"bytes on this rank"
                + (
                    f" (of {entry.nbytes} total over "
                    f"mp={entry.mp_degree} model-axis shards)"
                    if entry.mp_degree > 1 else ""
                )
                + f", over the whole TPUML_SERVE_HBM_BUDGET "
                f"({self._budget:.0f})"
            )
        return entry

    def register(self, name: str, model: Any) -> ResidentModel:
        """Adopt an in-memory fitted model: resolve its fast path, admit
        it against the HBM budget (evicting LRU residents), and warm its
        bucket ladder."""
        with self._lock:
            version = self._versions.get(name, 0) + 1
        entry = self._build_entry(name, model, version)
        with self._lock:
            self._entries.pop(name, None)
            self._entries[name] = entry
            self._versions[name] = entry.version
            self._admit_locked(keep=name)
            self._file_hbm_locked()
        if self._warmup and entry.coalesce:
            self.warm(entry)
        _LOGGER.info(
            "serving: registered %s v%d (family=%s engine=%s resident=%dB"
            " coalesce=%s)",
            name, entry.version, entry.family, entry.engine, entry.nbytes,
            entry.coalesce,
        )
        return entry

    def get(self, name: str) -> ResidentModel:
        """The resident entry for ``name`` (LRU-touched). A previously
        evicted model whose load path is known transparently reloads."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is not None:
                self._entries.move_to_end(name)
                return entry
            path = self._paths.get(name)
        if path is not None:
            return self.load(name, path)
        raise KeyError(f"model {name!r} is not registered")

    def evict(self, name: str) -> None:
        with self._lock:
            entry = self._entries.pop(name, None)
            if entry is None:
                return
            self._release(entry)
            self._evictions += 1
            self._file_hbm_locked()
        _LOGGER.info("serving: evicted %s (%dB)", name, entry.nbytes)

    # -- versioned hot-swap ------------------------------------------------
    def swap(
        self, name: str, model: Any = None, path: Optional[str] = None,
    ) -> ResidentModel:
        """Zero-downtime version flip: stage vN+1 beside the live vN,
        warm its full bucket ladder under warmup-flagged spans, then
        atomically replace the routing entry and release vN.

        The live entry is only touched by the final dict assignment
        under the registry lock, so a failure at ANY earlier stage
        (load, probe, warmup — including the ``swap:warm``/``swap:flip``
        fault-injection sites) leaves exactly one consistent version
        serving: the old one. Dispatchers resolve ``get(name)`` once per
        batch, so no batch ever mixes versions. The staged entry
        transiently occupies HBM beside vN (the "spare HBM" the swap
        story requires); after the flip ``_admit_locked`` restores the
        budget by LRU-evicting other residents if needed.

        Raises :class:`SwapError` (``.stage`` in ``load``/``warm``/
        ``flip``) on failure; the failure is also counted under
        ``swap_failures_total{model,stage}``. ``KeyError`` when ``name``
        was never registered — a swap needs a live version to replace
        (use :meth:`register`/:meth:`load` for v1)."""
        t0 = time.perf_counter()
        with self._lock:
            old = self._entries.get(name)
            if old is None:
                raise KeyError(
                    f"model {name!r} is not registered; swap replaces a "
                    "live version — register/load v1 first"
                )
            if name in self._swapping:
                raise SwapError(
                    f"a hot-swap of {name!r} is already in progress "
                    f"(stage {self._swapping[name]})", stage="load",
                )
            version = self._versions.get(name, old.version) + 1
            self._swapping[name] = "load"
        stage = "load"
        try:
            if model is None:
                if path is None:
                    raise ValueError("swap needs a model or a path")
                model = self._read_model(name, path)
            entry = self._build_entry(name, model, version)
            stage = "warm"
            with self._lock:
                self._swapping[name] = "warm"
            faults.fault_site("swap:warm")
            if self._warmup and entry.coalesce:
                self.warm(entry)
            stage = "flip"
            with self._lock:
                self._swapping[name] = "flip"
            faults.fault_site("swap:flip")
            with self._lock:
                old = self._entries.get(name)
                self._entries[name] = entry
                self._entries.move_to_end(name)
                self._versions[name] = entry.version
                # path hygiene: the evicted vN's reload path must not
                # dangle — record vN+1's path, or drop the stale one
                # when swapping in an in-memory model
                if path is not None:
                    self._paths[name] = path
                else:
                    self._paths.pop(name, None)
                self._admit_locked(keep=name)
                self._file_hbm_locked()
        except Exception as exc:
            telemetry.counter("swap_failures_total").inc(
                1, model=name, stage=stage
            )
            with self._lock:
                self._swapping.pop(name, None)
            if isinstance(exc, SwapError):
                raise
            raise SwapError(
                f"hot-swap of {name!r} to v{version} failed during "
                f"{stage}: {exc}", stage=stage,
            ) from exc
        with self._lock:
            self._swapping.pop(name, None)
        if old is not None and old.model is not entry.model:
            self._release(old)
            with self._lock:
                self._evictions += 1
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        telemetry.counter("swap_total").inc(1, model=name)
        telemetry.histogram("swap_duration_ms").observe(
            elapsed_ms, model=name
        )
        telemetry.gauge("serve_model_version").set(entry.version, model=name)
        _LOGGER.info(
            "serving: hot-swapped %s v%d -> v%d in %.1f ms "
            "(resident=%dB coalesce=%s)",
            name, old.version if old else 0, entry.version, elapsed_ms,
            entry.nbytes, entry.coalesce,
        )
        return entry

    def promote_alias(self, alias: str, name: str) -> ResidentModel:
        """Atomically re-route ``name`` to the (already warmed) entry
        registered under ``alias``, releasing the previous ``name``
        entry — the canary promotion flip: the candidate served shadow
        traffic under ``alias`` and now becomes the live version without
        a single cold dispatch."""
        with self._lock:
            entry = self._entries.pop(alias, None)
            if entry is None:
                raise KeyError(f"model {alias!r} is not registered")
            old = self._entries.get(name)
            entry.name = name
            self._entries[name] = entry
            self._entries.move_to_end(name)
            self._versions[name] = max(
                entry.version, self._versions.get(name, 0) + 1
            )
            entry.version = self._versions[name]
            alias_path = self._paths.pop(alias, None)
            if alias_path is not None:
                self._paths[name] = alias_path
            else:
                self._paths.pop(name, None)
            if old is not None:
                self._evictions += 1
            self._file_hbm_locked()
        if old is not None and old.model is not entry.model:
            self._release(old)
        telemetry.gauge("serve_model_version").set(entry.version, model=name)
        _LOGGER.info(
            "serving: promoted %s -> %s v%d", alias, name, entry.version
        )
        return entry

    # -- internals ---------------------------------------------------------
    def _resolve_mp(self, nbytes: int) -> int:
        """Model-axis degree charged for a model of ``nbytes``:
        constructor override first, else the ``TPUML_MESH_MP``
        resolution (1 when the env is unset — identical accounting to
        pre-replica serving). ``auto`` mode sizes against this model's
        own footprint, so only models too big for one HBM shard."""
        if self._mesh_mp is not None:
            return self._mesh_mp
        try:
            from ..parallel.mesh import resolve_mesh_mp

            return max(1, int(resolve_mesh_mp(float(nbytes))))
        except Exception:
            return 1

    def _admit_locked(self, keep: str) -> None:
        if self._budget is None:
            return
        while (
            sum(e.shard_nbytes for e in self._entries.values()) > self._budget
            and len(self._entries) > 1
        ):
            victim = next(n for n in self._entries if n != keep)
            entry = self._entries.pop(victim)
            self._release(entry)
            self._evictions += 1
            _LOGGER.info(
                "serving: LRU-evicted %s (%dB) for %s",
                victim, entry.shard_nbytes, keep,
            )

    @staticmethod
    def _release(entry: ResidentModel) -> None:
        """Drop every model-side cache holding device buffers; the
        arrays free when the closures go."""
        m = entry.model
        for attr in (
            "_transform_fn_cache",
            "_transform_engine_cache",
            "_ivf_index_cache",
        ):
            if getattr(m, attr, None) is not None:
                setattr(m, attr, {})
        if getattr(m, "_packed_cache", None) is not None:
            m._packed_cache = None

    def _file_hbm_locked(self) -> None:
        telemetry.record_hbm_estimate(
            "serve_registry",
            float(sum(e.shard_nbytes for e in self._entries.values())),
        )

    def warm(self, entry: ResidentModel) -> None:
        """Compile every padded bucket shape of ``entry`` now, each
        under its own ``serve.warmup.<name>.b<bucket>`` span site, so no
        steady-state dispatch ever carries a compile (and no single
        site accumulates enough to trip the retrace watchdog). Warmup
        compiles retry per ``TPUML_RETRIES`` (default 0 = single
        attempt) — a transient allocator hiccup at load time should not
        keep a model out of the registry."""
        from ..runtime import retry

        probe_row = np.zeros((1, entry.n_features), dtype=np.float32)
        for bucket in self.bucket_ladder():
            if bucket in entry.warmed:
                continue
            Xw = np.broadcast_to(
                probe_row, (bucket, entry.n_features)
            ).copy()

            def _compile_bucket(bucket: int = bucket, Xw: np.ndarray = Xw) -> None:
                with telemetry.span(
                    f"serve.warmup.{entry.name}.b{bucket}{self._rank_tag}",
                    bucket=bucket, warmup=True,
                ):
                    entry.fn(Xw)

            retry.with_retries(
                _compile_bucket, what=f"serve:warm:{entry.name}:b{bucket}"
            )
            entry.warmed.add(bucket)
