"""Logger factory (reference: ``/root/reference/python/src/spark_rapids_ml/utils.py:271-288``)."""

from __future__ import annotations

import logging
import sys
from typing import Any, Optional, Union

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"
_configured = False


def _ensure_configured() -> None:
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        root = logging.getLogger("spark_rapids_ml_tpu")
        if not root.handlers:
            root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True


def get_logger(
    cls: Union[type, str, Any], level: Optional[int] = None
) -> logging.Logger:
    """Per-class logger. ``level`` is only applied when explicitly given —
    a bare ``get_logger`` must never reset a level the user raised (e.g.
    the ``verbose=True`` framework kwarg); unset loggers inherit INFO from
    the package root."""
    _ensure_configured()
    if isinstance(cls, str):
        name = cls
    elif isinstance(cls, type):
        name = cls.__name__
    else:
        name = type(cls).__name__
    logger = logging.getLogger(f"spark_rapids_ml_tpu.{name}")
    if level is not None:
        logger.setLevel(level)
    return logger
