#!/usr/bin/env bash
# CI entry point (the reference's ci/test.sh:20-57 runs lint+typecheck, the
# pytest suite, then a benchmark smoke). tpuml-lint (stdlib-only, see
# docs/static_analysis.md) always runs; the third-party format/typecheck
# tools run when installed and are skipped (with a notice) otherwise — the
# framework environments are hermetic images where pip installs are not
# always possible.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== static checks =="
python -m compileall -q spark_rapids_ml_tpu benchmark tests tpuml_lint bench.py benchmark_runner.py
# tpuml-lint is stdlib-only so (unlike the tools below) it always runs:
# TPU/JAX invariants + env-var registry/doc drift. Rule catalog and
# suppression syntax: docs/static_analysis.md.
python -m tpuml_lint spark_rapids_ml_tpu benchmark tests scripts ci bench.py benchmark_runner.py
# concurrency-correctness rules, explicitly against an empty baseline:
# the lock-hierarchy (TPU010), blocking-under-lock (TPU011) and
# thread-lifecycle (TPU012) findings must be zero — fixed, never
# grandfathered (runtime/lockspec.py is the declared hierarchy)
python -m tpuml_lint spark_rapids_ml_tpu benchmark tests scripts ci bench.py benchmark_runner.py \
    --no-baseline --rule TPU010 --rule TPU011 --rule TPU012
python scripts/gen_config_docs.py --check
if python -c "import black" 2>/dev/null; then
    python -m black --check spark_rapids_ml_tpu tests benchmark
else
    echo "black not installed; skipping format check"
fi
if python -c "import isort" 2>/dev/null; then
    python -m isort --check-only spark_rapids_ml_tpu tests benchmark
else
    echo "isort not installed; skipping import-order check"
fi
if python -c "import mypy" 2>/dev/null; then
    python -m mypy spark_rapids_ml_tpu tpuml_lint
else
    echo "mypy not installed; skipping typecheck"
fi

echo "== unit tests =="
# Slow-marked tests (the 2-process distributed suite and runner smokes) run
# by default — they are the multi-chip correctness evidence and add <2 min.
# Set SKIPSLOW=1 for a quick iteration loop.
SKIPSLOW="${SKIPSLOW:-}"
if [ -n "$SKIPSLOW" ]; then
    python -m pytest tests/ -q
else
    python -m pytest tests/ -q --runslow
fi

echo "== notebooks (headless, CPU) =="
if python -c "import nbclient, nbformat, ipykernel" 2>/dev/null; then
    python ci/run_notebooks.py
else
    echo "nbclient/ipykernel not installed; skipping notebook execution"
fi

echo "== benchmark smoke =="
./run_benchmark.sh cpu 5000 64

echo "== transform bench smoke (rf packed engine + gbt + umap) =="
# Serving-path contract: the rf, gbt, and umap entries must emit
# transform_vs_baseline (BENCH_REQUIRE_TRANSFORM makes a silently
# dropped transform metric a hard failure), and the rf entry must carry
# the tree-batch provenance columns. Tiny CPU scales — this checks the
# metric plumbing, not the TPU throughput target.
JAX_PLATFORMS=cpu BENCH_ONLY=rf,gbt,umap BENCH_REQUIRE_TRANSFORM=rf,gbt,umap \
    BENCH_ROWS=4096 BENCH_RF_ROWS=4096 BENCH_RF_TREES=4 BENCH_RF_DEPTH=8 \
    BENCH_GBT_ROWS=4096 BENCH_GBT_ROUNDS=3 BENCH_GBT_DEPTH=4 \
    BENCH_UMAP_ROWS=1024 python bench.py > /tmp/tpuml_bench_tree.out
python - <<'EOF'
import json

with open("/tmp/tpuml_bench_tree.out") as f:
    line = json.loads(f.read().strip().splitlines()[-1])
rf, gbt = line["rf"], line["gbt"]
assert rf["tree_batch"] >= 1 and rf["hist_strategy"], rf
assert rf["seconds_per_level"] > 0, rf
assert "transform_vs_baseline" in gbt and gbt["seconds_per_round"] > 0, gbt
print(
    "bench rf/gbt columns OK: tree_batch", rf["tree_batch"],
    "hist", rf["hist_strategy"], "gbt engine", gbt["transform_engine"],
)
EOF

echo "== tree-batched growth dispatch + gbt fit/transform smoke =="
# TPUML_RF_TREE_BATCH contract: off and auto produce bit-identical
# forests at the same seed (batched growth is an execution-shape choice,
# never a semantics choice), bad values fail loudly, and the GBT
# estimators fit + transform end to end on the same engine stack.
JAX_PLATFORMS=cpu python - <<'EOF'
import os

import numpy as np

from spark_rapids_ml_tpu.classification import (
    GBTClassifier, RandomForestClassifier,
)
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.ops.tree_kernels import (
    ForestConfig, resolve_tree_batch,
)
from spark_rapids_ml_tpu.runtime import envspec

rng = np.random.default_rng(0)
X = rng.normal(size=(600, 16)).astype(np.float32)
y = (X[:, 0] - X[:, 2] > 0).astype(np.float64)
df = DataFrame({"features": X, "label": y})

kw = dict(numTrees=8, maxDepth=5, seed=3)
os.environ["TPUML_RF_TREE_BATCH"] = "off"
m_off = RandomForestClassifier(**kw).fit(df)
os.environ["TPUML_RF_TREE_BATCH"] = "auto"
m_auto = RandomForestClassifier(**kw).fit(df)
os.environ.pop("TPUML_RF_TREE_BATCH")
np.testing.assert_array_equal(m_off._features_arr, m_auto._features_arr)
np.testing.assert_array_equal(m_off._thresholds_arr, m_auto._thresholds_arr)
np.testing.assert_array_equal(m_off._leaf_stats_arr, m_auto._leaf_stats_arr)

cfg = ForestConfig(
    max_depth=4, n_bins=32, n_features=16, n_stats=2, impurity="gini",
    k_features=16, min_samples_leaf=1, min_info_gain=0.0,
    min_samples_split=2, bootstrap=True,
)
os.environ["TPUML_RF_TREE_BATCH"] = "nonsense"
try:
    resolve_tree_batch(8, cfg, 600)
except envspec.EnvSpecError:
    pass
else:
    raise SystemExit("TPUML_RF_TREE_BATCH=nonsense did not raise")
finally:
    os.environ.pop("TPUML_RF_TREE_BATCH")

model = GBTClassifier(maxIter=4, maxDepth=3, seed=1).fit(df)
out = model.transform(df)
acc = float((np.asarray(out["prediction"]) == y).mean())
assert acc > 0.9, acc
prob = np.asarray(out["probability"])
assert prob.shape == (600, 2)
np.testing.assert_allclose(prob.sum(axis=1), 1.0, atol=1e-5)
print(f"tree-batch dispatch + gbt smoke OK (gbt acc {acc:.3f})")
EOF

echo "== umap sgd engine dispatch smoke =="
# TPUML_UMAP_OPT contract: bad modes fail loudly, and on a CPU host both
# auto and an explicit pallas request resolve to the XLA engine (probe
# fallback) instead of crashing the fit.
JAX_PLATFORMS=cpu python - <<'EOF'
import os
from spark_rapids_ml_tpu.ops import umap_pallas as up

os.environ["TPUML_UMAP_OPT"] = "bogus"
try:
    up.resolve_umap_opt()
except ValueError:
    pass
else:
    raise SystemExit("TPUML_UMAP_OPT=bogus did not raise")
for mode in ("auto", "xla", "pallas"):
    os.environ["TPUML_UMAP_OPT"] = mode
    eng = up.select_sgd_engine(1024, 24, 2, 5)
    assert eng == "xla", (mode, eng)
os.environ.pop("TPUML_UMAP_OPT")
print("umap engine dispatch smoke OK")
EOF

echo "== ivf graph + ann kneighbors smoke =="
# TPUML_UMAP_GRAPH=ivf must drive a full UMAP fit through the IVF-Flat
# graph engine, and the ApproximateNearestNeighbors estimator must answer
# kneighbors through the probe search at recall >= 0.95 on blobs.
JAX_PLATFORMS=cpu python - <<'EOF'
import os

import numpy as np
from sklearn.datasets import make_blobs

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors
from spark_rapids_ml_tpu.umap import UMAP

X, _ = make_blobs(n_samples=2000, n_features=16, centers=12, random_state=7)
X = X.astype(np.float32)

os.environ["TPUML_UMAP_GRAPH"] = "ivf"
model = UMAP(
    n_neighbors=10, n_epochs=10, random_state=0, init="random",
    num_workers=1,
).fit(DataFrame({"features": X}))
assert model._fit_report["graph_engine"] == "ivf", model._fit_report
os.environ.pop("TPUML_UMAP_GRAPH")

os.environ["TPUML_ANN_GATE_ROWS"] = "1"
ann = ApproximateNearestNeighbors(k=15, num_workers=1).fit(
    DataFrame({"features": X})
)
_, _, knn_df = ann.kneighbors(DataFrame({"features": X[:128]}))
assert ann._ann_report["engine"] == "ivf", ann._ann_report
os.environ.pop("TPUML_ANN_GATE_ROWS")

from sklearn.neighbors import NearestNeighbors as SkNN

_, exact = SkNN(n_neighbors=15, algorithm="brute").fit(X).kneighbors(X[:128])
got = np.asarray(knn_df["indices"])
recall = np.mean([len(set(g) & set(e)) / 15 for g, e in zip(got, exact)])
assert recall >= 0.95, f"ann recall {recall:.4f} < 0.95"
print(f"ivf graph + ann smoke OK (recall {recall:.4f})")
EOF

echo "== fault-injection + checkpoint/resume smoke =="
# Resilience contract (docs/fault_tolerance.md): a fit killed mid-iteration
# by an injected preemption, refit with TPUML_CKPT_DIR set, resumes from
# the snapshot and matches the uninterrupted fit exactly.
JAX_PLATFORMS=cpu python - <<'EOF'
import os
import shutil
import tempfile

import numpy as np

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.runtime import counters, reset_faults
from spark_rapids_ml_tpu.runtime.faults import SimulatedPreemption

rng = np.random.default_rng(0)
X = rng.normal(size=(256, 5))
X[:64] += 4.0
df = DataFrame({"features": X})

def fit():
    return KMeans(
        k=4, maxIter=8, tol=1e-12, seed=5, num_workers=4,
        streaming=True, stream_chunk_rows=64,
    ).setFeaturesCol("features").fit(df)

clean = fit()

ckpt_dir = tempfile.mkdtemp(prefix="tpuml-ckpt-smoke-")
try:
    os.environ["TPUML_CKPT_DIR"] = ckpt_dir
    os.environ["TPUML_CKPT_EVERY"] = "1"
    os.environ["TPUML_FAULT_SPEC"] = "sgd:epoch:2:preempt"
    reset_faults()
    try:
        fit()
    except SimulatedPreemption:
        pass
    else:
        raise SystemExit("injected preemption did not fire")
    assert os.listdir(ckpt_dir), "no checkpoint committed before the fault"

    del os.environ["TPUML_FAULT_SPEC"]
    reset_faults()
    base = counters.snapshot()
    resumed = fit()
    delta = counters.delta_since(base)
    assert delta.get("resumed_fits") == 1, delta
    assert delta.get("resumed_from") == 2, delta
    np.testing.assert_allclose(
        resumed.cluster_centers_, clean.cluster_centers_, rtol=0, atol=1e-12
    )
    assert os.listdir(ckpt_dir) == [], "checkpoint not cleared on success"
finally:
    for var in ("TPUML_CKPT_DIR", "TPUML_CKPT_EVERY", "TPUML_FAULT_SPEC"):
        os.environ.pop(var, None)
    reset_faults()
    shutil.rmtree(ckpt_dir, ignore_errors=True)
print("fault-injection + resume smoke OK")
EOF

# Quantized-wire dispatch smoke: an int8 streamed PCA fit completes end
# to end, the model's ingest report carries the resolved encoding, and
# the components track the f32 fit within the documented int8 tolerance.
JAX_PLATFORMS=cpu python - <<'EOF'
import os

import numpy as np

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.runtime import counters

rng = np.random.default_rng(3)
X = rng.normal(size=(512, 8)).astype(np.float32)
df = DataFrame({"features": X})

def fit():
    return PCA(
        k=3, num_workers=4, streaming=True, stream_chunk_rows=64
    ).fit(df)

base = counters.snapshot()
m32 = fit()
assert m32._ingest_report["wire_dtype"] == "f32", m32._ingest_report
try:
    os.environ["TPUML_WIRE_DTYPE"] = "int8"
    m8 = fit()
finally:
    os.environ.pop("TPUML_WIRE_DTYPE", None)
assert m8._ingest_report["wire_dtype"] == "int8", m8._ingest_report
dots = np.abs((np.asarray(m32.components_) * np.asarray(m8.components_)).sum(axis=1))
np.testing.assert_allclose(dots, 1.0, atol=5e-2)
delta = counters.delta_since(base)
assert "wire_release_errors" not in delta, delta
print("quantized-wire dispatch smoke OK:", m8._ingest_report)
EOF

# Prefetch-ring overlap smoke: on a source whose decode is synthetically
# slow (sleeps release the GIL, so decode/stage/fold genuinely overlap
# even on the CPU backend), the pipelined pass must hide most of the
# slower leg: overlap_efficiency > 0.5 against independently timed legs.
JAX_PLATFORMS=cpu python - <<'EOF'
import contextlib
import time

import numpy as np

import jax.numpy as jnp

from spark_rapids_ml_tpu.data.chunks import Chunk, GeneratorChunkSource
from spark_rapids_ml_tpu.ops import streaming as st
from spark_rapids_ml_tpu.parallel.mesh import local_mesh

mesh = local_mesh()
chunk_rows, d, n_chunks = 8192, 256, 10
rows = chunk_rows * n_chunks
block = np.random.default_rng(0).standard_normal(
    (chunk_rows, d)).astype(np.float32)
mean0 = jnp.zeros((d,), jnp.float32)

def gen(start, count, seed):
    time.sleep(0.08)  # slow decode (object storage / parquet scan stand-in)
    return block[:count], None

def decode_leg():
    src = GeneratorChunkSource(gen, rows, d)
    for _ in src.iter_chunks(chunk_rows, np.float32):
        pass

def fold_leg(dev):
    acc = st.gram2_init(d, np.float32, False)
    for _ in range(n_chunks):
        acc = st.gram2_step(acc, dev["X"], dev["mask"], mean0)
    np.asarray(jnp.ravel(acc["G"])[:1])

def full_pass():
    src = GeneratorChunkSource(gen, rows, d)
    acc = st.gram2_init(d, np.float32, False)
    guard = st.StreamGuard()
    with contextlib.closing(
        st.iter_device_chunks(src, mesh, chunk_rows, np.float32,
                              need_y=False, need_w=False)
    ) as chunks:
        for _, dev in chunks:
            acc = st.gram2_step(acc, dev["X"], dev["mask"], mean0)
            guard.tick(dev, acc)
    guard.flush(acc)

dev0 = st.put_chunk(Chunk(X=block, n_valid=chunk_rows), mesh, np.float32)
fold_leg(dev0)  # compile outside the timers
t0 = time.perf_counter(); decode_leg(); t_decode = time.perf_counter() - t0
t0 = time.perf_counter(); fold_leg(dev0); t_fold = time.perf_counter() - t0
full_pass()  # warm the pipeline threads' first-iteration costs
# min over repeats: the smoke asserts the machinery CAN overlap, so
# scheduler noise should only forgive, never fail, the assertion
t_total = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    full_pass()
    t_total = min(t_total, time.perf_counter() - t0)
overlap = max(0.0, min(1.0, (t_decode + t_fold - t_total)
                       / max(min(t_decode, t_fold), 1e-9)))
print(f"ring overlap smoke: decode={t_decode:.3f}s fold={t_fold:.3f}s "
      f"total={t_total:.3f}s overlap_efficiency={overlap:.3f}")
assert overlap > 0.5, (t_decode, t_fold, t_total, overlap)
EOF

# bench pca_stream artifact: the JSON line must carry the new wire
# provenance columns
BENCH_ONLY=pca_stream BENCH_STREAM_SECONDS=3 BENCH_STREAM_CHUNK=65536 \
TPUML_WIRE_DTYPE=int8 JAX_PLATFORMS=cpu python bench.py cpu \
  > /tmp/tpuml_bench_wire.out
python - <<'EOF'
import json

with open("/tmp/tpuml_bench_wire.out") as f:
    line = json.loads(f.read().strip().splitlines()[-1])
entry = line["pca_stream"]
assert entry["wire_dtype"] == "int8", entry
assert "decode_seconds" in entry and "overlap_efficiency" in entry, entry
print("bench pca_stream wire columns OK:", entry["wire_dtype"],
      entry["ingest_gbps"], "GB/s logical")
EOF

echo "== gang-fit dispatch smoke =="
# TPUML_GANG_FIT=4 CV run must come back with gang provenance in every
# sub-model's _fit_report, and with the env UNSET the sequential path must
# be bit-identical across runs with zero gang counters (defaults inert).
JAX_PLATFORMS=cpu python - <<'EOF'
import os

import numpy as np

from spark_rapids_ml_tpu.classification import LogisticRegression
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
from spark_rapids_ml_tpu.runtime import counters
from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

rng = np.random.default_rng(0)
X = rng.normal(size=(1500, 12))
y = (X @ rng.normal(size=12) + 0.5 * rng.normal(size=1500) > 0).astype(float)
df = DataFrame({"features": X, "label": y})
lr = LogisticRegression(maxIter=15, tol=1e-6)
grid = (
    ParamGridBuilder()
    .addGrid(lr.getParam("regParam"), [0.01, 0.1])
    .addGrid(lr.getParam("elasticNetParam"), [0.0, 0.5])
    .build()
)
eva = MulticlassClassificationEvaluator(metricName="accuracy")

# defaults-inert: env unset, two runs bitwise identical, no gang counters
os.environ.pop("TPUML_GANG_FIT", None)
counters.reset()
a = [m for _, m in lr.fitMultiple(df, grid)]
b = [m for _, m in lr.fitMultiple(df, grid)]
for x, z in zip(a, b):
    assert np.array_equal(np.asarray(x.coef_), np.asarray(z.coef_))
    assert x._fit_report == {}
assert counters.get("gang_dispatches") == 0, counters.snapshot()

os.environ["TPUML_GANG_FIT"] = "4"
counters.reset()
cv = CrossValidator(
    estimator=lr, estimatorParamMaps=grid, evaluator=eva, numFolds=3,
    seed=1, collectSubModels=True,
)
model = cv.fit(df)
lanes = {
    m._fit_report.get("gang_lanes")
    for fold in model.subModels for m in fold
}
assert lanes and None not in lanes, lanes
assert max(lanes) <= 4, lanes  # pinned width respected
assert counters.get("gang_dispatches") >= 1, counters.snapshot()
assert counters.get("gang_lanes_total") == 12, counters.snapshot()
print(
    "gang-fit smoke OK: dispatches", counters.get("gang_dispatches"),
    "lane widths", sorted(lanes),
)
EOF

# bench logreg_multi artifact: the gang leg must carry its amortization
# columns (tiny CPU scale — metric plumbing, not the TPU 3x target)
BENCH_ONLY=logreg_multi BENCH_ROWS=20000 BENCH_COLS=64 \
JAX_PLATFORMS=cpu python bench.py cpu > /tmp/tpuml_bench_gang.out
python - <<'EOF'
import json

with open("/tmp/tpuml_bench_gang.out") as f:
    line = json.loads(f.read().strip().splitlines()[-1])
entry = line["logreg_multi"]
assert entry["gang_lanes"] == 24, entry
assert entry["solves_per_sec"] > 0 and entry["vs_sequential"] > 0, entry
assert "mfu" in entry and "seq_fit_seconds" in entry, entry
print(
    "bench logreg_multi columns OK: vs_sequential",
    round(entry["vs_sequential"], 2),
)
EOF

echo "== 2-D mesh (model-axis) smoke =="
# TPUML_MESH_MP contract: mp=2 fits of PCA/KMeans/ANN on 8 virtual CPU
# devices match the mp=1 fits within the documented f32 tolerance
# (docs/mesh.md), every sharded fit reports its mp_degree + per-shard
# bytes, defaults stay inert (env unset => empty _fit_report), and the
# sharded kernels compile once per program shape — zero retrace storms.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'EOF'
import os

import numpy as np
from sklearn.datasets import make_blobs

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors
from spark_rapids_ml_tpu.runtime import telemetry

X, _ = make_blobs(n_samples=2048, n_features=16, centers=8, random_state=11)
X = X.astype(np.float32)
df = DataFrame({"features": X})
qdf = DataFrame({"features": X[:128]})

def fit_all():
    pca = PCA(k=4).setInputCol("features").fit(df)
    km = KMeans(k=6, maxIter=20, seed=2).setFeaturesCol("features").fit(df)
    ann = ApproximateNearestNeighbors(k=10, num_workers=1).fit(df)
    _, _, knn = ann.kneighbors(qdf)
    return pca, km, ann, np.asarray(knn["indices"])

os.environ.pop("TPUML_MESH_MP", None)
os.environ["TPUML_ANN_GATE_ROWS"] = "1"
telemetry.reset_telemetry()
pca1, km1, ann1, ids1 = fit_all()
assert pca1._fit_report == {} and km1._fit_report == {}, "defaults not inert"
assert "mp_degree" not in ann1._ann_report, ann1._ann_report

os.environ["TPUML_MESH_MP"] = "2"
pca2, km2, ann2, ids2 = fit_all()
os.environ.pop("TPUML_MESH_MP")
os.environ.pop("TPUML_ANN_GATE_ROWS")

for report, bytes_key in (
    (pca2._fit_report, "gram_shard_bytes"),
    (km2._fit_report, "centroid_shard_bytes"),
    (ann2._ann_report, "index_shard_bytes"),
):
    assert report["mp_degree"] == 2 and report[bytes_key] > 0, report

np.testing.assert_allclose(
    np.abs(np.asarray(pca1.components_)),
    np.abs(np.asarray(pca2.components_)), rtol=2e-4, atol=2e-4,
)
np.testing.assert_allclose(
    np.sort(np.asarray(km1.cluster_centers_), axis=0),
    np.sort(np.asarray(km2.cluster_centers_), axis=0),
    rtol=1e-3, atol=1e-3,
)
overlap = np.mean([len(set(a) & set(b)) / 10 for a, b in zip(ids1, ids2)])
assert overlap >= 0.99, overlap

storms = telemetry.metrics_snapshot().get("retrace_storms")
assert not storms or all(s["value"] == 0 for s in storms["series"]), storms
print(f"2-D mesh smoke OK: mp_degree 2 for pca/kmeans/ann, "
      f"ann overlap {overlap:.3f}, 0 retrace storms")
EOF

echo "== telemetry trace smoke =="
# A traced streamed KMeans fit must produce a Perfetto-loadable trace
# whose spans cover the fit end to end: the root span brackets the whole
# wall time and its direct children account for >=95% of it, with the
# streaming pipeline sites all present.
rm -rf /tmp/tpuml_trace_smoke
TPUML_TRACE=/tmp/tpuml_trace_smoke JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os

import numpy as np

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.runtime import telemetry

rng = np.random.default_rng(0)
X = rng.normal(size=(8192, 16)).astype(np.float32)
df = DataFrame({"features": X})
PCA(k=3).setFeaturesCol("features").fit(df)
KMeans(
    k=4, maxIter=3, seed=0, num_workers=4, streaming=True,
    stream_chunk_rows=1024,
).setFeaturesCol("features").fit(df)
telemetry.flush()

stats = telemetry.span_stats()
for site in ("PCA.fit", "KMeans.fit", "preprocess", "fit.dispatch",
             "stream.ingest", "stream.decode", "stream.fold",
             "kmeans.lloyd_pass"):
    assert site in stats, (site, sorted(stats))

tdir = "/tmp/tpuml_trace_smoke"
traces = [f for f in os.listdir(tdir) if f.startswith("trace-")]
assert len(traces) == 1, os.listdir(tdir)
with open(os.path.join(tdir, traces[0])) as f:
    doc = json.load(f)  # Perfetto accepts exactly this JSON object form
events = doc["traceEvents"]
assert all(e["ph"] in ("X", "M", "i") for e in events), events[:3]
names = {e["name"] for e in events if e["ph"] == "X"}
assert {"KMeans.fit", "stream.ingest", "stream.decode",
        "stream.fold", "kmeans.lloyd_pass"} <= names, sorted(names)
# cross-thread parenting survived: every non-root span's parent exists
ids = {e["args"]["span_id"] for e in events if e["ph"] == "X"}
for e in events:
    if e["ph"] == "X" and "parent_id" in e["args"]:
        assert e["args"]["parent_id"] in ids, e
# the KMeans root's direct children account for >=95% of its wall time
xs = [e for e in events if e["ph"] == "X"]
root_ev = next(e for e in xs if e["name"] == "KMeans.fit")
covered = sum(
    e["dur"] for e in xs
    if e["args"].get("parent_id") == root_ev["args"]["span_id"]
)
assert covered >= 0.95 * root_ev["dur"], (covered, root_ev["dur"])
logs = [f for f in os.listdir(tdir) if f.startswith("events-")]
assert len(logs) == 1, os.listdir(tdir)
with open(os.path.join(tdir, logs[0])) as f:
    for line in f:
        json.loads(line)
# roofline attribution: compiled sites must carry measured cost-model
# numbers (XLA cost_analysis, not hand formulas) with an MFU + verdict
roofed = {
    site: st for site, st in stats.items()
    if "flops_total" in st and "mfu" in st
}
assert roofed, sorted(stats)
for site, st in roofed.items():
    assert st["flops_total"] > 0 and st["mfu"] > 0, (site, st)
    assert st["bound"] in ("compute", "memory"), (site, st)
print(f"telemetry trace smoke OK: {len(names)} span sites, "
      f"coverage {covered / root_ev['dur']:.3f}, "
      f"{len(roofed)} roofline-attributed sites")
EOF

# bench artifact with tracing on: every entry carries span provenance
# columns, and the run drops Prometheus/JSON metric dumps next to the
# trace
rm -rf /tmp/tpuml_trace_bench
BENCH_ONLY=pca_stream BENCH_STREAM_SECONDS=3 BENCH_STREAM_CHUNK=65536 \
TPUML_TRACE=/tmp/tpuml_trace_bench JAX_PLATFORMS=cpu python bench.py cpu \
  > /tmp/tpuml_bench_tele.out
python - <<'EOF'
import json
import os

with open("/tmp/tpuml_bench_tele.out") as f:
    line = json.loads(f.read().strip().splitlines()[-1])
entry = line["pca_stream"]
assert "device_seconds" in entry, entry
assert entry["spans"] and all(v >= 1 for v in entry["spans"].values()), entry
assert "suffstats.pass" in entry["spans"], entry
assert "stream.ingest" in entry["spans"], entry
# measured roofline MFU: cost-analysis FLOPs replace the hand formula,
# which survives as the labeled mfu_derived fallback
assert entry.get("flops_measured", 0) > 0, entry
assert "mfu_derived" in entry and entry["mfu"] > 0, entry
files = os.listdir("/tmp/tpuml_trace_bench")
assert any(f.startswith("metrics-") and f.endswith(".prom") for f in files), files
assert any(f.startswith("metrics-") and f.endswith(".json") for f in files), files
prom = [f for f in files if f.endswith(".prom")][0]
with open(os.path.join("/tmp/tpuml_trace_bench", prom)) as f:
    text = f.read()
assert "# TYPE tpuml_span_seconds summary" in text, text[:400]
print("bench telemetry columns OK:", sorted(entry["spans"])[:4], "...")
EOF

# defaults inert: with TPUML_TRACE unset nothing is recorded, nothing is
# written, and a traced fit's math is bit-identical to an untraced one
JAX_PLATFORMS=cpu python - <<'EOF'
import os
import tempfile

import numpy as np

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.runtime import telemetry

os.environ.pop("TPUML_TRACE", None)
rng = np.random.default_rng(5)
X = rng.normal(size=(2048, 8)).astype(np.float32)
df = DataFrame({"features": X})

def fit():
    return KMeans(k=3, maxIter=5, seed=0).setFeaturesCol("features").fit(df)

plain = fit()
assert telemetry.span_stats() == {}, telemetry.span_stats()
assert telemetry.flush() is None and telemetry.write_metrics() is None
assert telemetry.span("x") is telemetry.span("y")  # shared no-op singleton

tdir = tempfile.mkdtemp(prefix="tpuml-tele-inert-")
try:
    os.environ["TPUML_TRACE"] = tdir
    traced = fit()
finally:
    os.environ.pop("TPUML_TRACE", None)
assert np.asarray(plain.cluster_centers_).tobytes() == \
    np.asarray(traced.cluster_centers_).tobytes()
print("telemetry defaults-inert smoke OK")
EOF

echo "== bench-regress gate smoke =="
# Synthetic trajectory: a fabricated prior run plus a current run with
# one entry perturbed past the ±15% threshold must exit nonzero naming
# the offender; the unperturbed pair must pass.
python - <<'EOF'
import json
import os
import subprocess
import sys
import tempfile

def wrapper(n, entries):
    tail = json.dumps(
        {"metric": "pca_fit_throughput", "value": 1.0, **entries}
    )
    return {"n": n, "cmd": "python bench.py", "rc": 0,
            "tail": "log noise\n" + tail, "parsed": None}

def entry(sec, vs, mfu):
    return {"samples_per_sec_per_chip": 1e6, "fit_seconds": sec,
            "vs_baseline": vs, "mfu": mfu}

with tempfile.TemporaryDirectory() as td:
    base = {"pca": entry(1.0, 2.0, 0.2), "kmeans": entry(2.0, 3.0, 0.3)}
    with open(os.path.join(td, "BENCH_r01.json"), "w") as f:
        json.dump(wrapper(1, base), f)
    ok = {"pca": entry(1.05, 1.95, 0.21), "kmeans": entry(1.9, 3.1, 0.29)}
    with open(os.path.join(td, "BENCH_r02.json"), "w") as f:
        json.dump(wrapper(2, ok), f)
    r = subprocess.run(
        [sys.executable, "scripts/bench_regress.py",
         "--trajectory", os.path.join(td, "BENCH_r*.json")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, (r.returncode, r.stdout)

    bad = dict(ok, kmeans=entry(2.5, 3.1, 0.29))  # +31% seconds
    with open(os.path.join(td, "BENCH_r03.json"), "w") as f:
        json.dump(wrapper(3, bad), f)
    r = subprocess.run(
        [sys.executable, "scripts/bench_regress.py",
         "--trajectory", os.path.join(td, "BENCH_r*.json")],
        capture_output=True, text=True,
    )
    assert r.returncode != 0, (r.returncode, r.stdout)
    assert "kmeans.fit_seconds" in r.stdout, r.stdout
print("bench-regress synthetic gate OK")
EOF
# the real recorded trajectory must be clean (newest vs prior run)
python scripts/bench_regress.py

echo "== multi-host trace merge smoke =="
# Two simulated ranks (the launcher's TPUML_PROC_ID layout) trace into
# one shared directory; merge_traces must fold the shards into a single
# Perfetto file with both host tracks and summed counters.
rm -rf /tmp/tpuml_merge_smoke
for RANK in 0 1; do
    TPUML_TRACE=/tmp/tpuml_merge_smoke TPUML_PROC_ID=$RANK \
    TPUML_NUM_PROCS=2 JAX_PLATFORMS=cpu python - <<'EOF'
import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.runtime import telemetry

@jax.jit
def f(x):
    return (x @ x.T).sum()

with telemetry.span("merge.fit"):
    f(jnp.ones((32, 32), jnp.float32)).block_until_ready()
telemetry.flush()
telemetry.write_metrics()
EOF
done
python scripts/merge_traces.py /tmp/tpuml_merge_smoke
python - <<'EOF'
import json
import os

tdir = "/tmp/tpuml_merge_smoke"
shards = [f for f in os.listdir(tdir)
          if f.startswith("trace-r") and f.endswith(".json")]
assert len(shards) == 2, shards
with open(os.path.join(tdir, "merged.json")) as f:
    doc = json.load(f)
assert doc["metadata"]["hosts"] == [0, 1], doc["metadata"]
tracks = {
    e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
    if e.get("ph") == "M" and e.get("name") == "process_name"
}
assert set(tracks) == {0, 1}, tracks
assert all(name.startswith("host") for name in tracks.values()), tracks
spans = [e for e in doc["traceEvents"]
         if e.get("ph") == "X" and e["name"] == "merge.fit"]
assert {e["pid"] for e in spans} == {0, 1}, spans
# aggregated counters stay consistent: merged spans_recorded == the sum
# over the per-rank snapshots == the span events in the merged trace
snaps = []
for fn in os.listdir(tdir):
    if fn.startswith("metrics-r") and fn.endswith(".json"):
        with open(os.path.join(tdir, fn)) as f:
            snaps.append(json.load(f))
per_rank = sum(s["spans_recorded"]["series"][0]["value"] for s in snaps)
with open(os.path.join(tdir, "merged-metrics.json")) as f:
    merged = json.load(f)
total = merged["spans_recorded"]["series"][0]["value"]
assert total == per_rank == len(spans) == 2, (total, per_rank, len(spans))
print(f"merge_traces smoke OK: hosts {sorted(tracks)}, "
      f"{total} spans across ranks")
EOF

echo "== serving runtime smoke =="
# In-process serving tier under trace: three co-resident families, a
# mixed-shape request sweep, and the hard gates — zero retrace storms,
# zero compiles attributed to the steady-state dispatch site, served
# outputs bit-identical to direct transforms, and a sane p99.
rm -rf /tmp/tpuml_trace_serve
JAX_PLATFORMS=cpu python - <<'EOF'
import os
import time

import numpy as np

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.models.tree import RandomForestClassifier
from spark_rapids_ml_tpu.models.umap import UMAP
from spark_rapids_ml_tpu.runtime import telemetry
from spark_rapids_ml_tpu.serving import ServingRuntime

rng = np.random.default_rng(19)
X = rng.normal(size=(512, 12)).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)
df = DataFrame({"features": X, "label": y})
models = {
    "pca": PCA(k=3).fit(df),
    "rf": RandomForestClassifier(
        numTrees=4, maxDepth=4, seed=3, num_workers=1
    ).fit(df),
    "umap": UMAP(
        n_neighbors=5, n_epochs=15, random_state=3, num_workers=1
    ).fit(DataFrame({"features": X})),
}
queries = [rng.normal(size=(s, 12)).astype(np.float32)
           for s in (1, 2, 5, 13, 17, 33)]
# trace ONLY the serving tier: the storm gate is a serving contract,
# and a traced fit legitimately compiles many programs per site
os.environ["TPUML_TRACE"] = "/tmp/tpuml_trace_serve"
telemetry.reset_telemetry()
t0 = time.perf_counter()
with ServingRuntime(batch_window_us=1000, max_bucket_rows=64) as rt:
    for name, m in models.items():
        rt.register(name, m)
    for _rep in range(3):
        futs = [(name, q, rt.predict_async(name, q))
                for name in models for q in queries]
        for name, q, f in futs:
            out = f.result(300)
            direct = models[name].transform(DataFrame({"features": q}))
            for col, served in out.items():
                assert np.array_equal(served, np.asarray(direct[col])), (
                    name, col, q.shape)
elapsed = time.perf_counter() - t0

snap = telemetry.metrics_snapshot()
storms = snap.get("retrace_storms")
assert not storms or all(s["value"] == 0 for s in storms["series"]), storms
batch_compiles = [
    s for s in snap.get("xla_compiles", {}).get("series", [])
    if s["labels"].get("site") == "serve.batch"
]
assert batch_compiles == [], batch_compiles
stats = telemetry.span_stats()
assert stats["serve.batch"]["count"] > 0, sorted(stats)
p99 = snap["serve_p99_ms"]["series"]
assert {s["labels"]["model"] for s in p99} == set(models), p99
assert elapsed < 120, elapsed
print(f"serving smoke OK: {3 * len(models) * len(queries)} requests, "
      f"0 retrace storms, dispatch site compile-free")
EOF

echo "== live ops plane smoke =="
# Ops-plane contract (docs/observability.md): defaults inert (no env =>
# no socket, no thread), /metrics + /statusz + /healthz answered
# mid-streamed-fit with well-formed Prometheus/JSON, and a forced SLO
# burn producing exactly one flight dump tagged slo_burn.
rm -rf /tmp/tpuml_ops_smoke
JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os
import threading
import time
import urllib.request

import numpy as np

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.runtime import opsplane, telemetry

flight_dir = "/tmp/tpuml_ops_smoke"

# defaults inert: no env => ensure_started refuses, no socket, no thread
for var in ("TPUML_OPS_PORT", "TPUML_FLIGHT_DIR", "TPUML_TRACE"):
    os.environ.pop(var, None)
assert opsplane.ensure_started() is False
assert opsplane.address() is None and opsplane.flight_recorder() is None
assert not [t for t in threading.enumerate()
            if t.name.startswith(("tpuml-ops", "tpuml-slo"))]

# live scrape mid-fit: the streamed ingest loop auto-starts the plane;
# the scrape fires from a span sink on the first completed stream.fold,
# so it provably lands while chunks are still folding
os.environ["TPUML_OPS_PORT"] = "0"
os.environ["TPUML_FLIGHT_DIR"] = flight_dir
os.environ["TPUML_SLO_EVAL_MS"] = "60000"  # ticks driven manually below

rng = np.random.default_rng(0)
X = rng.normal(size=(4096, 8)).astype(np.float32)
df = DataFrame({"features": X})

scrapes = []

def get(path):
    host, port = opsplane.address()
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=30
    ) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()

def scrape_on_fold(ev, thread_name):
    if ev.get("name") == "stream.fold" and not scrapes:
        t0 = time.perf_counter()
        m = get("/metrics")
        dt = time.perf_counter() - t0
        scrapes.append((m, get("/statusz"), get("/healthz"), dt))

telemetry.add_span_sink(scrape_on_fold)
try:
    KMeans(
        k=4, maxIter=3, seed=0, num_workers=2, streaming=True,
        stream_chunk_rows=256,
    ).setFeaturesCol("features").fit(df)
finally:
    telemetry.remove_span_sink(scrape_on_fold)

assert opsplane.started(), "streamed fit did not auto-start the plane"
assert scrapes, "no scrape landed mid-fit"
(mcode, mctype, mbody), (scode, _, sbody), (hcode, _, hbody), dt = scrapes[0]
assert mcode == 200 and mctype.startswith("text/plain"), (mcode, mctype)
lines = mbody.decode().splitlines()
assert any(l.startswith("# TYPE tpuml_") for l in lines), lines[:5]
for l in lines:
    if l and not l.startswith("#"):
        name = l.split("{", 1)[0].split(" ", 1)[0]
        assert name.startswith("tpuml_"), l
        float(l.rsplit(" ", 1)[1])  # every sample parses as a number
assert hcode == 200 and json.loads(hbody) == {"status": "ok"}
assert scode == 200
st = json.loads(sbody)
assert "stream.ingest" in {s["name"] for s in st["active_spans"]}, st
assert "stream_ingest" in st["heartbeat_ages_s"], st

# forced SLO burn: two violating ticks alert once and trigger the
# one-shot flight dump — a third burning tick must not dump again
ev = opsplane._EVALUATOR
for _ in range(8):
    telemetry.histogram("serve_p99_ms").observe(1e4, model="smoke")
ev.tick(now=1000.0)
burn = ev.tick(now=1001.0)
assert burn["serving_p99_ms"]["alerting"], burn
ev.tick(now=1002.0)
assert telemetry.counter("slo_burn_alerts").value(slo="serving_p99_ms") == 1
shards = [f for f in os.listdir(flight_dir) if f.startswith("flight-")]
assert len(shards) == 1, shards
with open(os.path.join(flight_dir, shards[0])) as f:
    doc = json.load(f)
assert doc["metadata"]["flight"] is True, doc["metadata"]
assert doc["metadata"]["reason"] == "slo_burn", doc["metadata"]
assert opsplane.flight_recorder().dumps == {"slo_burn": 1}
print(f"ops plane smoke OK: {dt * 1e3:.1f} ms mid-fit /metrics scrape, "
      "one-shot burn dump")
EOF

# killed-run crash dump: a streamed fit SIGTERMed mid-flight with
# TPUML_TRACE unset still leaves a loadable rank-tagged flight shard
# (the handler dumps the ring, then chains to the default disposition
# so the exit status stays the conventional -SIGTERM).
rm -rf /tmp/tpuml_flight_smoke
python - <<'EOF'
import json
import os
import signal
import subprocess
import sys

child = r'''
import numpy as np
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.runtime import telemetry

def announce(ev, thread_name):
    # announce on the THIRD fold: this sink can run before the flight
    # recorder's for the same event, so earlier folds being announced
    # guarantees at least two are already in the ring when the parent
    # reacts and the SIGTERM lands
    if ev.get("name") == "stream.fold":
        announce.folds += 1
        if announce.folds == 3:
            print("MIDFIT", flush=True)

announce.folds = 0
telemetry.add_span_sink(announce)
rng = np.random.default_rng(0)
X = rng.normal(size=(2048, 8)).astype(np.float32)
df = DataFrame({"features": X})
while True:  # fit until killed
    KMeans(
        k=4, maxIter=50, seed=0, num_workers=2, streaming=True,
        stream_chunk_rows=64,
    ).setFeaturesCol("features").fit(df)
'''

env = dict(os.environ)
for var in ("TPUML_TRACE", "TPUML_OPS_PORT"):
    env.pop(var, None)
env["TPUML_FLIGHT_DIR"] = "/tmp/tpuml_flight_smoke"
env["JAX_PLATFORMS"] = "cpu"
proc = subprocess.Popen(
    [sys.executable, "-c", child], env=env,
    stdout=subprocess.PIPE, text=True,
)
line = proc.stdout.readline()
assert "MIDFIT" in line, line
proc.send_signal(signal.SIGTERM)
rc = proc.wait(timeout=120)
proc.stdout.close()
assert rc == -signal.SIGTERM, rc
shards = [f for f in os.listdir("/tmp/tpuml_flight_smoke")
          if f.startswith("flight-")]
assert len(shards) == 1, shards
with open(os.path.join("/tmp/tpuml_flight_smoke", shards[0])) as f:
    doc = json.load(f)
assert doc["metadata"]["flight"] is True, doc["metadata"]
assert doc["metadata"]["reason"] == "signal", doc["metadata"]
names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
assert "stream.fold" in names, sorted(names)[:20]
print(f"crash-dump smoke OK: {shards[0]} with {len(names)} span sites")
EOF

echo "== serving chaos smoke =="
# Fault-injected serving (docs/serving.md resilience contract): an OOM
# dispatch splits the group and retries halves bit-identically, repeated
# dispatch faults trip the per-model breaker (fast-fail at admission,
# half-open probe closes it again), every future resolves — no hangs —
# and drain() reports a clean flush.
JAX_PLATFORMS=cpu python - <<'EOF'
import concurrent.futures
import os
import time

import numpy as np

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.runtime import faults, telemetry
from spark_rapids_ml_tpu.serving import Overloaded, ServingRuntime

rng = np.random.default_rng(23)
X = rng.normal(size=(256, 10)).astype(np.float32)
model = PCA(k=3).fit(DataFrame({"features": X}))

# dispatch 0 = the coalesced 4-request group (oom -> halve), 3/4 = the
# two singleton dispatches after the halves (1/2) -> breaker opens
os.environ["TPUML_FAULT_SPEC"] = (
    "serve:dispatch:0:oom,serve:dispatch:3:raise,serve:dispatch:4:raise"
)
faults.reset_faults()
telemetry.reset_telemetry()
queries = [rng.normal(size=(2, 10)).astype(np.float32) for _ in range(4)]
with ServingRuntime(
    batch_window_us=30_000, max_bucket_rows=64,
    breaker_fails=2, breaker_cooldown_ms=200,
) as rt:
    rt.register("pca", model)
    # one coalesced group; the injected RESOURCE_EXHAUSTED must be
    # absorbed by halving, outputs bit-identical to direct transforms
    futs = [rt.predict_async("pca", q) for q in queries]
    for q, f in zip(queries, futs):
        out = f.result(120)
        direct = model.transform(DataFrame({"features": q}))
        for col, served in out.items():
            assert np.array_equal(served, np.asarray(direct[col])), col
    # two injected dispatch faults -> breaker opens -> typed fast-fail
    for _ in range(2):
        try:
            rt.predict("pca", queries[0])
            raise AssertionError("injected dispatch fault did not surface")
        except RuntimeError as e:
            assert "injected" in str(e).lower(), e
    assert rt.breaker_states() == {"pca": "open"}, rt.breaker_states()
    try:
        rt.predict("pca", queries[0])
        raise AssertionError("open breaker admitted a request")
    except Overloaded as e:
        assert e.reason == "breaker_open", e.reason
    time.sleep(0.3)  # past cooldown: half-open probe succeeds -> closed
    rt.predict("pca", queries[0])
    assert rt.breaker_states() == {"pca": "closed"}, rt.breaker_states()
    report = rt.drain(timeout=30)
    assert report == {"drained": True, "aborted": 0}, report
    done, not_done = concurrent.futures.wait(futs, timeout=0)
    assert not not_done, not_done

snap = telemetry.metrics_snapshot()
inj = {s["labels"]["kind"]: s["value"]
       for s in snap["fault_injections"]["series"]}
assert inj == {"oom": 1, "raise": 2}, inj
assert "serve_breaker_state" in snap, sorted(snap)
shed = {(s["labels"]["model"], s["labels"]["reason"]): s["value"]
        for s in snap["serve_shed_total"]["series"]}
assert shed == {("pca", "breaker_open"): 1}, shed
del os.environ["TPUML_FAULT_SPEC"]
print("serving chaos smoke OK: oom halved bit-identically, breaker "
      "open->half-open->closed, drain clean, zero hung futures")
EOF

echo "== serving overload smoke =="
# Overload contract under trace: offered load past measured capacity
# into a tiny bounded queue must shed (typed, counted) while goodput
# stays positive and the retrace-storm gate holds.
rm -rf /tmp/tpuml_trace_overload
JAX_PLATFORMS=cpu python - <<'EOF'
import os
import time

import numpy as np

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.runtime import telemetry
from spark_rapids_ml_tpu.serving import Overloaded, ServingRuntime

rng = np.random.default_rng(29)
X = rng.normal(size=(256, 10)).astype(np.float32)
model = PCA(k=3).fit(DataFrame({"features": X}))
q = rng.normal(size=(8, 10)).astype(np.float32)

os.environ["TPUML_TRACE"] = "/tmp/tpuml_trace_overload"
telemetry.reset_telemetry()
with ServingRuntime(
    batch_window_us=1000, max_bucket_rows=32, queue_limit=4
) as rt:
    rt.register("pca", model)
    # closed-loop capacity probe (stays under the queue bound)
    t0 = time.perf_counter()
    for _ in range(3):
        for f in [rt.predict_async("pca", q) for _ in range(4)]:
            f.result(120)
    capacity_qps = 12 / max(time.perf_counter() - t0, 1e-9)
    offered = 2 * capacity_qps
    ok = shed = 0
    futs = []
    t0 = time.perf_counter()
    for i in range(200):
        lag = t0 + i / offered - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        try:
            futs.append(rt.predict_async("pca", q))
        except Overloaded as e:
            assert e.reason == "queue_full", e.reason
            shed += 1
    for f in futs:
        f.result(120)
        ok += 1
    elapsed = time.perf_counter() - t0

snap = telemetry.metrics_snapshot()
storms = snap.get("retrace_storms")
assert not storms or all(s["value"] == 0 for s in storms["series"]), storms
sheds = {s["labels"]["reason"]: s["value"]
         for s in snap["serve_shed_total"]["series"]}
assert shed > 0 and sheds.get("queue_full") == shed, (shed, sheds)
goodput = ok / elapsed
assert ok > 0 and goodput > 0, (ok, elapsed)
del os.environ["TPUML_TRACE"]
print(f"serving overload smoke OK: {shed}/200 shed at 2x capacity, "
      f"goodput {goodput:.0f} qps, 0 retrace storms")
EOF

echo "== pod-scale router smoke =="
# Fleet contract (docs/serving.md pod-scale section): a 2-replica
# loopback fleet serves a mixed-shape stream bit-identically with zero
# retrace storms, /statusz's fleet section reports both ranks with the
# merged-reservoir p99, and a replica killed mid-stream resolves its
# in-flight futures with typed errors — never a hang — while the
# survivor keeps the fleet serving.
JAX_PLATFORMS=cpu TPUML_OPS_PORT=0 python - <<'EOF'
import json
import urllib.request

import numpy as np

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.runtime import opsplane, telemetry
from spark_rapids_ml_tpu.runtime.admission import ShuttingDown
from spark_rapids_ml_tpu.serving import Router

rng = np.random.default_rng(37)
X = rng.normal(size=(256, 10)).astype(np.float32)
model = PCA(k=3).fit(DataFrame({"features": X}))
telemetry.reset_telemetry()
assert opsplane.ensure_started()

with Router(
    replicas=2, policy="p2c",
    runtime_kwargs=dict(batch_window_us=2_000, max_bucket_rows=32),
) as router:
    router.register("pca", model)
    queries = [rng.normal(size=(s, 10)).astype(np.float32)
               for s in (1, 2, 5, 13, 1, 17, 3, 8) * 3]
    futs = [router.predict_async("pca", q) for q in queries]
    for q, f in zip(queries, futs):
        out = f.result(120)
        direct = model.transform(DataFrame({"features": q}))
        for col, served in out.items():
            assert np.array_equal(served, np.asarray(direct[col])), col

    host, port = opsplane.address()
    with urllib.request.urlopen(
        f"http://{host}:{port}/statusz", timeout=30
    ) as r:
        st = json.loads(r.read())
    routers = st["fleet"]["routers"]
    assert len(routers) == 1 and routers[0]["healthy"] == 2, routers
    assert [rep["rank"] for rep in routers[0]["replicas"]] == [0, 1], routers
    assert routers[0]["warmup"]["ready"] is True, routers[0]
    assert routers[0]["p99_ms"].get("pca", 0) > 0, routers[0]

    # chaos: replica 0 dies with requests still in flight — those
    # futures resolve served-or-typed, and the survivor keeps serving
    inflight = [router.replicas[0].predict_async("pca", queries[1])
                for _ in range(4)]
    router.replicas[0].close()
    for f in inflight:
        try:
            f.result(30)  # served before the close landed — fine
        except ShuttingDown:
            pass  # typed, never a hang
    assert router.healthy_count() == 1
    outs = [router.predict("pca", q, timeout=120) for q in queries[:8]]
    assert len(outs) == 8

snap = telemetry.metrics_snapshot()
storms = snap.get("retrace_storms")
assert not storms or all(s["value"] == 0 for s in storms["series"]), storms
picks = {s["labels"]["replica"]: s["value"]
         for s in snap["router_picks_total"]["series"]}
assert picks.get("0", 0) > 0 and picks.get("1", 0) > 0, picks
print("pod-scale router smoke OK: both ranks in /statusz, replica kill "
      "survived, 0 retrace storms")
EOF

echo "== fit scheduler chaos smoke =="
# Multi-tenant fit scheduler (docs/scheduler.md contract): an injected
# sched:dispatch fault fails exactly one tenant while survivors stay
# bitwise equal to their solo fits, a 1 ms quantum preempts a streamed
# fit at checkpoint boundaries and the resumed result matches the
# uninterrupted twin, and drain-under-load resolves every future.
rm -rf /tmp/tpuml_sched_ckpt
JAX_PLATFORMS=cpu python - <<'EOF'
import concurrent.futures
import os

import numpy as np

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.runtime import FitScheduler, faults, telemetry
from spark_rapids_ml_tpu.runtime.faults import InjectedFault

rng = np.random.default_rng(31)
dfs = [
    DataFrame({"features": rng.normal(size=(96 + 16 * i, 3 + i)).astype(np.float32)})
    for i in range(4)
]
make = lambda i: KMeans(k=2 + i % 2, maxIter=5, seed=7 + i, num_workers=4)
solo = [np.asarray(make(i).fit(df).cluster_centers_) for i, df in enumerate(dfs)]

# dispatch order == submit order (no deadlines, equal priority): the
# injected fault at hit index 1 lands on tenant t1 and only t1
os.environ["TPUML_FAULT_SPEC"] = "sched:dispatch:1:raise"
faults.reset_faults()
telemetry.reset_telemetry()
with FitScheduler() as sched:
    futs = [sched.submit(make(i), df, tenant=f"t{i}") for i, df in enumerate(dfs)]
    for i, f in enumerate(futs):
        if i == 1:
            try:
                f.result(300)
                raise AssertionError("injected dispatch fault did not surface")
            except InjectedFault:
                pass
        else:
            assert np.array_equal(np.asarray(f.result(300).cluster_centers_), solo[i]), i
    stats = sched.stats()
assert stats["dispatches"] == 3 and stats["dispatch_errors"] == 1, stats
del os.environ["TPUML_FAULT_SPEC"]
faults.reset_faults()

# quantum preemption: streamed kmeans checkpoints + yields every ~1 ms,
# resumes to the exact uninterrupted result
X = rng.normal(size=(256, 5)).astype(np.float64)
X[:64] += 4.0
stream_df = DataFrame({"features": X})
mk = lambda: KMeans(k=4, maxIter=6, tol=1e-12, seed=5, num_workers=4,
                    streaming=True, stream_chunk_rows=64)
clean = mk().fit(stream_df)
os.environ["TPUML_CKPT_DIR"] = "/tmp/tpuml_sched_ckpt"
os.environ["TPUML_CKPT_EVERY"] = "1"
with FitScheduler(quantum_ms=1.0) as sched:
    model = sched.fit(mk(), stream_df, timeout=300)
    stats = sched.stats()
assert stats["preemptions"] >= 1, stats
assert stats["resumes"] == stats["preemptions"], stats
np.testing.assert_allclose(
    model.cluster_centers_, clean.cluster_centers_, rtol=0, atol=1e-12
)
del os.environ["TPUML_CKPT_DIR"], os.environ["TPUML_CKPT_EVERY"]

# drain under load: every admitted future resolves (model or typed
# ShuttingDown) inside the timeout — zero hangs
sched = FitScheduler()
futs = [sched.submit(make(i % 4), dfs[i % 4], tenant=f"t{i}") for i in range(6)]
report = sched.drain(timeout=120)
done, not_done = concurrent.futures.wait(futs, timeout=0)
assert not not_done, not_done
assert report["aborted"] == sum(1 for f in futs if f.exception() is not None), report
print(f"fit scheduler chaos smoke OK: 1 injected fault isolated, "
      f"{stats['preemptions']} preemptions resumed bit-identically, "
      f"drain {report}")
EOF

echo "== lifecycle hot-swap chaos smoke =="
# Continuous-training lifecycle (docs/serving.md#lifecycle contract):
# a v2 re-fit through the scheduler hot-swaps under live traffic with
# zero typed sheds and exactly one resident version; an injected
# swap:warm fault surfaces as a typed SwapError with v1 untouched and
# still serving; a divergent canary rolls back automatically and the
# version breaker refuses the immediate retry.
JAX_PLATFORMS=cpu python - <<'EOF'
import os
import threading
import time

import numpy as np

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.runtime import FitScheduler, faults, telemetry
from spark_rapids_ml_tpu.serving import (
    LifecycleError, ModelLifecycle, ServingRuntime, SwapError,
)

telemetry.reset_telemetry()
faults.reset_faults()
rng = np.random.default_rng(19)
X = rng.normal(size=(512, 8)).astype(np.float32)
df = DataFrame({"features": X})
queries = [rng.normal(size=(s, 8)).astype(np.float32) for s in (3, 17, 33)]

def totals(name):
    s = telemetry.metrics_snapshot().get(name)
    return sum(row["value"] for row in s["series"]) if s else 0

with ServingRuntime(batch_window_us=5000, max_bucket_rows=64) as rt:
    rt.register("pca", PCA(k=4).fit(df))
    with FitScheduler() as sched:
        lc = ModelLifecycle(rt, scheduler=sched)
        # live closed-loop traffic across the whole swap window
        stop, errors = threading.Event(), []
        def client():
            i = 0
            while not stop.is_set():
                try:
                    rt.predict("pca", queries[i % 3], timeout=300)
                except Exception as e:
                    errors.append(e)
                    return
                i += 1
        t = threading.Thread(target=client)
        t.start()
        try:
            # v2 re-fit through the scheduler as a preemptible tenant,
            # handed straight to the swap path
            v2 = sched.submit(
                PCA(k=4), df, tenant="lifecycle", priority=-1,
                aging_ms=600000.0,
            ).result(300)
            entry = lc.swap("pca", model=v2)
            assert entry.version == 2, entry.version
            time.sleep(0.3)
        finally:
            stop.set()
            t.join(60)
        assert not errors, f"typed shed under swap: {errors[0]!r}"
        assert rt.registry.names() == ["pca"], rt.registry.names()
        assert totals("serve_shed_total") == 0
        assert totals("retrace_storms") == 0
        # served output matches the v2 model exactly
        direct = v2.transform(DataFrame({"features": queries[1]}))
        out = rt.predict("pca", queries[1], timeout=300)
        for col in out:
            assert np.array_equal(out[col], np.asarray(direct[col])), col

        # injected mid-swap fault: typed, counted, v2 untouched
        os.environ["TPUML_FAULT_SPEC"] = "swap:warm:0:raise"
        faults.reset_faults()
        try:
            lc.swap("pca", model=PCA(k=4).fit(df))
            raise AssertionError("injected swap:warm fault did not surface")
        except SwapError as e:
            assert e.stage == "warm", e.stage
        del os.environ["TPUML_FAULT_SPEC"]
        faults.reset_faults()
        assert rt.registry.get("pca").version == 2
        assert not rt.registry.swaps_in_progress()
        assert totals("swap_failures_total") == 1
        rt.predict("pca", queries[0], timeout=300)  # still serving

        # divergent canary (fitted on unrelated data — its projection
        # basis disagrees): auto-rollback + version breaker opens
        other = rng.normal(size=(512, 8)).astype(np.float32)
        bad = PCA(k=4).fit(DataFrame({"features": other}))
        lc.start_canary("pca", model=bad, fraction=1.0, min_requests=4)
        for _ in range(8):
            rt.predict("pca", queries[2], timeout=300)
        deadline = time.monotonic() + 30
        while lc.canary_in_progress("pca") and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not lc.canary_in_progress("pca"), "canary never settled"
        assert rt.registry.get("pca").version == 2  # v2 kept serving
        assert totals("canary_rollbacks_total") == 1
        assert totals("canary_promotions_total") == 0
        try:
            lc.swap("pca", model=v2)
            raise AssertionError("version breaker admitted a swap")
        except LifecycleError:
            pass
        lc.drain(timeout=30)
print("lifecycle chaos smoke OK: scheduled re-fit hot-swapped with zero "
      "sheds, injected swap fault typed + rolled past, divergent canary "
      "rolled back with breaker open")
EOF

echo "== lock-witness chaos smoke =="
# The whole stack — serving burst + scheduler re-fit + lifecycle
# hot-swap + canary — under TPUML_LOCK_WITNESS=1: every cataloged lock
# is an instrumented wrapper checking the runtime/lockspec.py rank
# hierarchy on the REAL cross-thread acquisition orders (client
# threads, the dispatcher, the fit loop, canary scoring). The contract:
# zero lock-order violations, zero retrace storms, and the hold-time
# histogram populated for the data-plane locks the burst exercised.
JAX_PLATFORMS=cpu TPUML_LOCK_WITNESS=1 python - <<'EOF'
import threading
import time

import numpy as np

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.runtime import FitScheduler, lockwitness, telemetry
from spark_rapids_ml_tpu.serving import ModelLifecycle, ServingRuntime

telemetry.reset_telemetry()
lockwitness.reset_lockwitness()
assert lockwitness.active(), "witness not armed"
rng = np.random.default_rng(23)
X = rng.normal(size=(512, 8)).astype(np.float32)
df = DataFrame({"features": X})
queries = [rng.normal(size=(s, 8)).astype(np.float32) for s in (3, 17, 33)]

def totals(name):
    s = telemetry.metrics_snapshot().get(name)
    return sum(row["value"] for row in s["series"]) if s else 0

with ServingRuntime(batch_window_us=5000, max_bucket_rows=64) as rt:
    rt.register("pca", PCA(k=4).fit(df))
    with FitScheduler() as sched:
        lc = ModelLifecycle(rt, scheduler=sched)
        stop, errors = threading.Event(), []
        def client(i0):
            i = i0
            while not stop.is_set():
                try:
                    rt.predict("pca", queries[i % 3], timeout=300)
                except Exception as e:
                    errors.append(e)
                    return
                i += 1
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        try:
            # a scheduled re-fit hot-swapped under the burst, then a
            # promoting canary — the full cross-subsystem lock surface
            v2 = sched.submit(
                PCA(k=4), df, tenant="lifecycle", priority=-1,
                aging_ms=600000.0,
            ).result(300)
            lc.swap("pca", model=v2)
            lc.start_canary(
                "pca", model=v2, fraction=1.0, min_requests=4
            )
            deadline = time.monotonic() + 60
            while (lc.canary_in_progress("pca")
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert not lc.canary_in_progress("pca"), "canary never settled"
            time.sleep(0.3)
        finally:
            stop.set()
            for t in threads:
                t.join(60)
        assert not errors, f"typed shed under witness: {errors[0]!r}"
        lc.drain(timeout=30)

viol = lockwitness.violations()
assert viol == (), f"lock-order violations on real paths: {viol}"
assert totals("lock_order_violations_total") == 0
assert totals("retrace_storms") == 0
held = {
    row.get("labels", {}).get("lock")
    for row in telemetry.metrics_snapshot()["lock_hold_ms"]["series"]
}
assert "serving.state" in held, held
print("lock-witness chaos smoke OK: serving burst + scheduled re-fit + "
      "hot-swap + canary under TPUML_LOCK_WITNESS=1 — zero lock-order "
      "violations, zero retrace storms, hold histograms for "
      f"{len(held)} lock(s)")
EOF

echo "== measured-autotuner smoke =="
# Autotuner contract (docs/autotune.md): defaults inert (env unset =>
# no cache file, no autotune metric series, fits bit-identical), a cold
# probe search measures real pinned-width fits and persists the winner,
# and the warm re-run answers the resolver's consult from the cache
# with ZERO new probe spans (span-count-asserted under TPUML_TRACE) and
# zero retrace storms.
rm -rf /tmp/tpuml_autotune_smoke /tmp/tpuml_autotune_trace
JAX_PLATFORMS=cpu python - <<'EOF'
import os
import time

import numpy as np

from spark_rapids_ml_tpu.classification import RandomForestClassifier
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.runtime import autotune, telemetry

cache_dir = "/tmp/tpuml_autotune_smoke"
os.makedirs(cache_dir)
rng = np.random.default_rng(7)
X = rng.normal(size=(512, 12)).astype(np.float32)
y = (X[:, 0] - X[:, 1] > 0).astype(np.float64)
df = DataFrame({"features": X, "label": y})

def fit():
    return RandomForestClassifier(
        numTrees=8, maxDepth=5, seed=3, num_workers=1
    ).fit(df)

def probe_spans():
    return sum(
        st["count"]
        for name, st in telemetry.span_stats().items()
        if name.startswith("autotune.probe.")
    )

def metric_total(name):
    s = telemetry.metrics_snapshot().get(name)
    return sum(r["value"] for r in s["series"]) if s else 0

# --- defaults inert: no file, no metric series, bit-identical fits ---
for var in ("TPUML_AUTOTUNE", "TPUML_AUTOTUNE_CACHE", "TPUML_TRACE"):
    os.environ.pop(var, None)
os.environ["TPUML_RF_TREE_BATCH"] = "auto"
telemetry.reset_telemetry()
autotune.reset_autotune()
m_a, m_b = fit(), fit()
np.testing.assert_array_equal(m_a._features_arr, m_b._features_arr)
np.testing.assert_array_equal(m_a._thresholds_arr, m_b._thresholds_arr)
assert "autotuned" not in m_a._fit_report, m_a._fit_report
assert not any(
    k.startswith("autotune") for k in telemetry.metrics_snapshot()
)
assert os.listdir(cache_dir) == [], "off mode must not create files"

# --- cold: real measured search over pinned widths, winner persisted ---
os.environ["TPUML_AUTOTUNE"] = "on"
os.environ["TPUML_AUTOTUNE_CACHE"] = cache_dir
os.environ["TPUML_TRACE"] = "/tmp/tpuml_autotune_trace"
# each candidate measure is a full (small) fit: the library's 2 s
# default budget is sized for micro-probes and would truncate the grid
os.environ["TPUML_AUTOTUNE_BUDGET_MS"] = "60000"
telemetry.reset_telemetry()
autotune.reset_autotune()
m_cold = fit()  # heuristic-provenance decision carries the shape key
dec = next(
    d for d in m_cold._fit_report["autotuned"] if d["knob"] == "rf_tree_batch"
)
assert dec["provenance"] == "heuristic", dec

def measure(width):
    os.environ["TPUML_RF_TREE_BATCH"] = str(width)
    os.environ["TPUML_AUTOTUNE"] = "off"  # no recursion inside probes
    try:
        t0 = time.perf_counter()
        fit()
        return time.perf_counter() - t0
    finally:
        os.environ["TPUML_RF_TREE_BATCH"] = "auto"
        os.environ["TPUML_AUTOTUNE"] = "on"

widths = [dec["value"]] + [w for w in (1, 2, 4) if w != dec["value"]]
won = autotune.probe("rf_tree_batch", dec["key"], widths, measure, reps=1)
cold_spans = probe_spans()
assert cold_spans >= len(widths), (cold_spans, widths)
# one SEARCH (probes_total) spanning len(widths) measurements (spans)
assert metric_total("autotune_probes_total") == 1
assert os.path.exists(os.path.join(cache_dir, "autotune-cache.json"))

# --- warm: fresh in-memory state answers from disk, zero new probes ---
autotune.reset_autotune()  # simulate a new process on the same cache
m_warm = fit()
warm = next(
    d for d in m_warm._fit_report["autotuned"] if d["knob"] == "rf_tree_batch"
)
assert warm["provenance"] == "cache_hit", warm
assert warm["value"] == won.value, (warm, won)
assert probe_spans() == cold_spans, "warm cache must probe ZERO times"
assert metric_total("autotune_probes_total") == 1, "no new searches warm"
assert metric_total("autotune_cache_hits") >= 1
storms = telemetry.metrics_snapshot().get("retrace_storms")
assert not storms or all(
    s["value"] == 0 for s in storms["series"]
), storms
for var in ("TPUML_AUTOTUNE", "TPUML_AUTOTUNE_CACHE", "TPUML_TRACE",
            "TPUML_RF_TREE_BATCH", "TPUML_AUTOTUNE_BUDGET_MS"):
    os.environ.pop(var, None)
print(f"autotuner smoke OK: cold search measured {cold_spans} probes "
      f"(winner {won.value}, {won.provenance}), warm consult cache_hit "
      "with zero new probes, 0 retrace storms")
EOF

# bench autotune artifact: the tuned-vs-default A/B must post its ratio
# columns and clear the bench_regress absolute floor (tiny CPU scale —
# this checks the search + gate plumbing, not TPU speedups)
JAX_PLATFORMS=cpu BENCH_ONLY=autotune BENCH_AUTOTUNE_BUDGET_MS=20000 \
    BENCH_AUTOTUNE_RF_ROWS=2048 python bench.py cpu \
    > /tmp/tpuml_bench_autotune.out
python - <<'EOF'
import json
import subprocess
import sys

with open("/tmp/tpuml_bench_autotune.out") as f:
    line = json.loads(f.read().strip().splitlines()[-1])
entry = line["autotune"]
assert entry["tuned_vs_default"] >= 0.85, entry
legs = entry["legs"]
assert set(legs) == {"rf", "pca_stream", "serving"}, sorted(legs)
for name, leg in legs.items():
    assert leg["tuned_vs_default"] > 0, (name, leg)
    # default-wins legs must show the tuner RETURNING the default
    if leg["tuned"] == leg["default"]:
        assert leg["tuned_vs_default"] == 1.0, (name, leg)
r = subprocess.run(
    [sys.executable, "scripts/bench_regress.py",
     "--current", "/tmp/tpuml_bench_autotune.out",
     "--trajectory", "/tmp/tpuml_nonexistent_r*.json"],
    capture_output=True, text=True,
)
assert "tuned_vs_default>=floor" in r.stdout, r.stdout
assert r.returncode == 0, (r.returncode, r.stdout)
print("bench autotune columns OK:",
      {k: v["tuned_vs_default"] for k, v in legs.items()})
EOF

echo "CI OK"
