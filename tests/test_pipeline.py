"""Pipeline / OneVsRest meta-algorithm tests (the reference composes with
pyspark's versions — ``classification.py:318-321`` — so the framework
ships drop-ins with the same semantics)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import LogisticRegression
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.pipeline import (
    OneVsRest,
    OneVsRestModel,
    Pipeline,
    PipelineModel,
)


def _multiclass(n=450, d=8, k=3, seed=0, spread=1.0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * 4
    y = rng.integers(0, k, size=n)
    X = centers[y] + spread * rng.normal(size=(n, d))
    return X.astype(np.float32), y.astype(np.float64)


def test_pipeline_pca_then_logreg(tmp_path):
    X, y = _multiclass()
    df = DataFrame({"features": X, "label": y})
    pipe = Pipeline(stages=[
        PCA(k=4, inputCol="features", outputCol="pca_out"),
        LogisticRegression(featuresCol="pca_out", regParam=0.01, num_workers=2),
    ])
    model = pipe.fit(df)
    out = model.transform(df)
    preds = np.asarray(out.column("prediction"))
    assert (preds == y).mean() > 0.9

    # persistence round-trip: chained transform must match
    path = str(tmp_path / "pipe")
    model.write().overwrite().save(path)
    loaded = PipelineModel.load(path)
    preds2 = np.asarray(loaded.transform(df).column("prediction"))
    np.testing.assert_array_equal(preds, preds2)


def test_pipeline_transformer_stage_passthrough():
    X, y = _multiclass(n=200)
    df = DataFrame({"features": X, "label": y})
    pca_model = PCA(k=3, inputCol="features", outputCol="p").fit(df)
    pipe = Pipeline(stages=[
        pca_model,  # already-fitted transformer stage
        LogisticRegression(featuresCol="p", regParam=0.01),
    ])
    model = pipe.fit(df)
    assert model.stages[0] is pca_model
    out = model.transform(df)
    assert "prediction" in out


def test_one_vs_rest_matches_multinomial(tmp_path):
    X, y = _multiclass(n=500, d=6, k=4, spread=1.5)
    df = DataFrame({"features": X, "label": y})
    ovr_model = OneVsRest(
        classifier=LogisticRegression(regParam=0.01, num_workers=2)
    ).fit(df)
    assert ovr_model.numClasses == 4
    out = ovr_model.transform(df)
    preds = np.asarray(out.column("prediction"))
    acc_ovr = (preds == y).mean()

    direct = LogisticRegression(regParam=0.01, num_workers=2).fit(df)
    acc_direct = (
        np.asarray(direct.transform(df).column("prediction")) == y
    ).mean()
    assert acc_ovr > 0.9
    assert acc_ovr >= acc_direct - 0.05

    raw = np.asarray(out.column("rawPrediction"))
    assert raw.shape == (500, 4)

    path = str(tmp_path / "ovr")
    ovr_model.save(path)
    loaded = OneVsRestModel.load(path)
    preds2 = np.asarray(loaded.transform(df).column("prediction"))
    np.testing.assert_array_equal(preds, preds2)


def test_one_vs_rest_rejects_bad_labels():
    X, _ = _multiclass(n=60)
    df = DataFrame({"features": X, "label": np.linspace(0, 1, 60)})
    with pytest.raises(RuntimeError, match="non-negative integers"):
        OneVsRest(classifier=LogisticRegression()).fit(df)
