"""IVF-Flat approximate kNN: MXU coarse quantization + probe-list scan.

TPU-native analog of the reference's IVF-Flat ``ApproximateNearestNeighbors``
backend (cuML ``NearestNeighborsMG`` with ``algorithm="ivfflat"``). The
index partitions the item set into ``nlist`` Voronoi cells of a k-means
coarse quantizer; a query scans only its ``nprobe`` closest cells instead
of every row. Both hot steps are MXU-shaped tall-skinny matmuls — the
regime the TPU linear-algebra literature targets (see PAPERS.md):

* **coarse quantization** (build + search): one ``pairwise_sq_dists``
  contraction against the (nlist, d) centroid table;
* **probe scan** (search): per-probe candidate gather + a batched
  query-row x candidate-block contraction, folded into a running top-k
  through the same ``_tile_top_k`` (PartialReduce) machinery as the exact
  ring — so ``TPUML_KNN_TOPK`` applies here unchanged.

Index layout: rows are cluster-sorted (CSR ``offsets``/``lens`` kept as
metadata) and then scattered into a *capacity-padded* layout — list ``l``
owns slots ``[l*cap, (l+1)*cap)`` with padding slots carrying ``+inf``
squared norm / id ``-1``. The pad makes every per-probe gather a static
``(qc, cap)`` window (no ragged CSR arithmetic inside jit); ``cap`` is
the observed max list length under a *loosely* balanced assignment —
rows spill to their second-closest list only above a hard
``3 * n / nlist`` bound, so pathological skew cannot blow up the padded
scan while routine cell-size variation keeps its nearest centroid
(a tight 1.25x bound was measured to spill ~20% of rows and cap recall
at ~0.93 regardless of nprobe).

A fused Pallas scan-and-top-k kernel was evaluated and deliberately NOT
built: the probe scan's item operand is a per-query HBM gather (each query
row addresses a different candidate window), so there is no shared
VMEM-resident item block for a kernel to exploit — unlike the dense
distance tile ``knn_pallas.py`` fuses. See ``docs/ann_performance.md``.

Distribution: queries are dp-sharded exactly like ``ring_knn``'s query
side; the (replicated) index arrays ride ``LAYOUT.replicated()`` specs. Rotating index
shards around the ring — the exact path's layout — would multiply the
sparse gather passes by ``n_dev`` without reducing per-device work, since
a probe touches O(nprobe * cap) rows wherever they live.
"""

from __future__ import annotations

import functools
import logging
import math
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding

from ._compat import shard_map
from ..parallel.layout import LAYOUT
from ..parallel.mesh import DP_AXIS, MP_AXIS
from .kmeans_kernels import kmeans_lloyd, pairwise_sq_dists
from .knn_kernels import _tile_top_k

_LOGGER = logging.getLogger("spark_rapids_ml_tpu.umap")

# balanced-assignment HARD capacity: ceil(_BALANCE_HARD * n / nlist). Rows
# only spill to their second-closest list above this bound, so the spill
# is reserved for pathological skew (a hot cluster soaking up the dataset)
# instead of routine cell-size variation. A tight bound (1.25x was
# measured) spills ~20% of rows on blob data and caps recall at ~0.93
# regardless of nprobe — a spilled row lives in a list its queries rank
# ~30th of 63; at 2.0x the plateau is still visible (0.985). The padded
# slot count of a healthy index is the OBSERVED max list length
# (data-dependent but host-static), not this bound.
_BALANCE_HARD = 3.0
_CAP_MULTIPLE = 8

# coarse-quantizer training: Lloyd on a bounded sample (IVF quality needs
# cell shapes, not converged centroids — 10 iterations on <=2^18 rows is
# the standard recipe) chunked at _TRAIN_CSIZE rows per device pass.
_TRAIN_SAMPLE = 1 << 18
_TRAIN_ITERS = 10
_TRAIN_CSIZE = 4096

# assignment pass chunk (build): (chunk, nlist) distance tile.
_ASSIGN_CHUNK = 16384

# search-time gathered tile budget, in f32 elements: the (qc, cap, d)
# per-probe candidate gather is the live intermediate; qc adapts so it
# stays ~256 MB regardless of cap * d.
_GATHER_BUDGET_ELEMS = 64 * 1024 * 1024

# hard feasibility floor: below this the index build (sample + Lloyd +
# balance) costs more than the exact sweep it displaces.
_MIN_IVF_ROWS = 256
# every list must expect at least this many rows or the quantizer is
# fragmenting the data (empty/singleton cells -> recall collapse).
_MIN_ROWS_PER_LIST = 4


# --------------------------------------------------------------------------
# env resolution + parameter heuristics (resolved OUTSIDE jit; the values
# participate in static args / host control flow only)
# --------------------------------------------------------------------------


def resolve_umap_graph() -> str:
    """Validated ``TPUML_UMAP_GRAPH`` (auto | exact | ivf)."""
    from ..runtime import envspec

    return str(envspec.get("TPUML_UMAP_GRAPH"))


def mp_ivf_shards(mesh, nlist: int) -> int:
    """Resolved model-axis degree for list-sharded IVF search: the mesh's
    mp extent when ``TPUML_MP_IVF`` is on and there are at least mp lists,
    else 1. Reads the env OUTSIDE jit."""
    from ..runtime import envspec

    from ..parallel.mesh import MP_AXIS

    n_mp = int(mesh.shape.get(MP_AXIS, 1))
    if n_mp <= 1 or nlist < n_mp:
        return 1
    if str(envspec.get("TPUML_MP_IVF")) == "off":
        return 1
    return n_mp


def resolve_ann_gate_rows() -> int:
    """Validated ``TPUML_ANN_GATE_ROWS`` — the auto-dispatch row floor."""
    from ..runtime import envspec

    return int(envspec.get("TPUML_ANN_GATE_ROWS"))


def default_nlist(n_rows: int) -> int:
    """sqrt(n)-scaled list count — the standard IVF sizing (cells of
    ~sqrt(n) rows balance quantization cost against scan cost)."""
    return max(2, min(int(round(math.sqrt(max(n_rows, 4)))), n_rows // 2))


def default_nprobe(nlist: int) -> int:
    """nlist/8 probes (~12.5% of lists), floored at 6 — the measured
    recall>=0.95 operating point on clustered data at the default nlist
    (see docs/ann_performance.md for the trade-off table). The floor only
    binds below nlist=48, where a tiny quantizer slices clusters finely
    enough that a fixed list fraction misses boundary neighbors — and
    where scanning a few extra (small) lists costs almost nothing."""
    return min(nlist, max(6, -(-nlist // 8)))


def hard_capacity(n_rows: int, nlist: int) -> int:
    """The enforced per-list row bound (spill threshold)."""
    cap = -(-int(_BALANCE_HARD * n_rows) // nlist)
    return -(-max(cap, 1) // _CAP_MULTIPLE) * _CAP_MULTIPLE


def resolve_ann_params(
    n_rows: int,
    nlist: Optional[int] = None,
    nprobe: Optional[int] = None,
) -> Tuple[int, int]:
    """Resolve + validate (nlist, nprobe) for an ``n_rows``-item index.

    Explicit arguments (estimator ``algoParams``) win over the
    ``TPUML_ANN_NLIST`` / ``TPUML_ANN_NPROBE`` env overrides, which win
    over the heuristics. Raises ``ValueError`` on out-of-domain values —
    the estimator surfaces these verbatim.
    """
    from ..runtime import autotune, envspec

    tuned = None
    if (nlist is None or nprobe is None) and autotune.active():
        # tuned winners (bench probe or kneighbors' in-situ recall-gated
        # search) fill only the slots neither algoParams nor env pinned
        tuned = autotune.consult(
            "ann_params", autotune.shape_key(n=n_rows)
        )
        if not (
            isinstance(tuned, (list, tuple))
            and len(tuned) == 2
            and all(isinstance(v, int) for v in tuned)
        ):
            tuned = None
    if nlist is None:
        nlist = envspec.get("TPUML_ANN_NLIST")
    if nlist is None and tuned is not None and 2 <= tuned[0] <= max(n_rows, 1):
        nlist = tuned[0]
    if nlist is None:
        nlist = default_nlist(n_rows)
    nlist = int(nlist)
    if nlist < 2:
        raise ValueError(f"ivfflat nlist={nlist} must be >= 2")
    if nlist > max(n_rows, 1):
        raise ValueError(
            f"ivfflat nlist={nlist} must be <= number of index rows {n_rows}"
        )
    if nprobe is None:
        nprobe = envspec.get("TPUML_ANN_NPROBE")
    if nprobe is None and tuned is not None and tuned[0] == nlist:
        # a tuned nprobe is only meaningful at the nlist it was measured
        # against — a stale pair from another nlist falls through
        if 1 <= tuned[1] <= nlist:
            nprobe = tuned[1]
    if nprobe is None:
        nprobe = default_nprobe(nlist)
    nprobe = int(nprobe)
    if nprobe < 1:
        raise ValueError(f"ivfflat nprobe={nprobe} must be >= 1")
    if nprobe > nlist:
        raise ValueError(
            f"ivfflat nprobe={nprobe} must be <= nlist={nlist}"
        )
    return nlist, nprobe


def ivf_feasible(n_rows: int, k: int, nlist: int, nprobe: int) -> bool:
    """Shape gate: can an (nlist, nprobe) index answer k-NN on n_rows
    sanely? False when the build would cost more than it saves, when the
    cells would fragment, or when the probed candidate pool cannot even
    hold k rows."""
    if n_rows < _MIN_IVF_ROWS or k >= n_rows:
        return False
    if nlist < 2 or n_rows < _MIN_ROWS_PER_LIST * nlist:
        return False
    # conservative candidate-pool floor: probed lists must plausibly hold
    # k real rows (padding slots carry +inf and never fill a slot). Cell
    # sizes vary, so budget each probed list at 1/4 of the mean.
    min_per_list = n_rows // int(_BALANCE_HARD * nlist) or 1
    return nprobe * min_per_list >= k


def select_graph_engine(
    n_rows: int,
    k: int,
    *,
    nlist: Optional[int] = None,
    nprobe: Optional[int] = None,
) -> str:
    """Resolve ``TPUML_UMAP_GRAPH`` against the feasibility gate: returns
    ``"ivf"`` or ``"exact"``. An explicit ``ivf`` that the gate rejects
    warns and falls back — the fit must not crash on a shape the index
    cannot serve (same clean-fallback contract as ``select_sgd_engine``).
    ``auto`` additionally requires ``n_rows >= TPUML_ANN_GATE_ROWS`` so
    unconfigured fits keep the exact graph bit-identically."""
    mode = resolve_umap_graph()
    if mode == "exact":
        return "exact"
    try:
        nl, npb = resolve_ann_params(n_rows, nlist=nlist, nprobe=nprobe)
        feasible = ivf_feasible(n_rows, k, nl, npb)
        reason = "below the IVF feasibility gate"
    except ValueError as e:  # env/param combo invalid for this shape
        feasible = False
        reason = str(e)
    if mode == "ivf":
        if feasible:
            return "ivf"
        _LOGGER.warning(
            "TPUML_UMAP_GRAPH=ivf but the IVF graph engine is unavailable "
            "for config (n_rows=%d, k=%d): %s; falling back to the exact "
            "brute-force graph",
            n_rows, k, reason,
        )
        return "exact"
    if feasible and n_rows >= resolve_ann_gate_rows():
        return "ivf"
    return "exact"


# --------------------------------------------------------------------------
# index build
# --------------------------------------------------------------------------


class IvfIndex(NamedTuple):
    """Built index: device arrays + host CSR metadata.

    ``grouped_*`` use the capacity-padded cluster-grouped layout (list
    ``l`` at slots ``[l*cap, (l+1)*cap)``); ``offsets``/``lens`` are the
    CSR description of the underlying cluster-sorted ordering.
    """

    centroids: jax.Array    # (nlist, d) f32 coarse quantizer
    grouped_x: jax.Array    # (nlist*cap, d) f32, zero-filled padding
    grouped_sq: jax.Array   # (nlist*cap,) f32 ||x||^2, +inf on padding
    grouped_ids: jax.Array  # (nlist*cap,) int32 source row ids, -1 padding
    offsets: np.ndarray     # (nlist+1,) int64 CSR starts (compact order)
    lens: np.ndarray        # (nlist,) int32 valid rows per list
    cap: int                # static padded list length
    nlist: int
    n_rows: int


@functools.partial(jax.jit, static_argnames=("chunk",))
def _assign_top2(
    X: jax.Array, centers: jax.Array, *, chunk: int
) -> Tuple[jax.Array, jax.Array]:
    """Two closest centroids per row: (d2 (n, 2) ascending, idx (n, 2)).

    The second choice is the balancer's spill target; its distance gap is
    the spill cost. Chunked so the (chunk, nlist) tile bounds HBM.
    """
    n = X.shape[0]
    pad = (-n) % chunk
    Xp = jnp.pad(X, ((0, pad), (0, 0)))
    c_sq = (centers * centers).sum(axis=1)

    def body(_, xc):
        d2 = pairwise_sq_dists(xc, centers, c_sq)
        negd, idx = lax.top_k(-d2, 2)
        return None, (-negd, idx)

    _, (d2, idx) = lax.scan(body, None, Xp.reshape(-1, chunk, X.shape[1]))
    return d2.reshape(-1, 2)[:n], idx.reshape(-1, 2)[:n]


def _balanced_assign(
    d2_2: np.ndarray, idx_2: np.ndarray, nlist: int, cap: int
) -> np.ndarray:
    """Capacity-balanced list assignment (host): start from the nearest
    centroid, then spill each overfull list's cheapest-to-move rows
    (smallest second-choice distance gap) to their second choice; a rare
    final pass routes any still-overfull remainder to the least-loaded
    lists. Total capacity ``nlist*cap > n`` guarantees termination."""
    first = idx_2[:, 0].astype(np.int64)
    counts = np.bincount(first, minlength=nlist)
    if counts.max() <= cap:
        return first
    assign = first.copy()
    margin = d2_2[:, 1] - d2_2[:, 0]
    for l in np.flatnonzero(counts > cap):
        rows = np.flatnonzero(first == l)
        spill = rows[
            np.argsort(margin[rows], kind="stable")[: counts[l] - cap]
        ]
        assign[spill] = idx_2[spill, 1]
    counts = np.bincount(assign, minlength=nlist)
    while counts.max() > cap:
        for l in np.flatnonzero(counts > cap):
            rows = np.flatnonzero(assign == l)
            spill = rows[
                np.argsort(margin[rows], kind="stable")[: counts[l] - cap]
            ]
            for r in spill:
                tgt = int(np.argmin(counts))
                assign[r] = tgt
                counts[tgt] += 1
                counts[l] -= 1
    return assign


def build_ivf_index(
    X: np.ndarray,
    *,
    nlist: int,
    seed: int,
    mesh: Optional[Mesh] = None,
    max_iter: int = _TRAIN_ITERS,
) -> IvfIndex:
    """Train the coarse quantizer and lay out the cluster-grouped index.

    Deterministic for a given (X, nlist, seed): the sample draw, seeding
    and balancer are all host numpy under ``default_rng(seed)``, and the
    Lloyd/assignment device passes are plain f32 XLA.
    """
    from ..parallel.mesh import make_mesh, shard_rows

    X = np.ascontiguousarray(np.asarray(X, dtype=np.float32))
    n, d = X.shape
    rng = np.random.default_rng(seed)

    # 1) coarse quantizer: Lloyd on a bounded sample, reusing the shared
    # kmeans machinery (chunked stats + psum; Pallas fused step when
    # eligible). An IVF quantizer needs cell shapes, not convergence.
    if n > _TRAIN_SAMPLE:
        sample = X[rng.choice(n, _TRAIN_SAMPLE, replace=False)]
    else:
        sample = X
    centers0 = sample[rng.choice(sample.shape[0], nlist, replace=False)]
    if mesh is None:
        mesh = make_mesh()
    Xs_d, ms_d = shard_rows(sample, mesh, row_multiple=_TRAIN_CSIZE)
    centers, _, _ = kmeans_lloyd(
        Xs_d,
        ms_d,
        jnp.asarray(centers0),
        mesh=mesh,
        csize=_TRAIN_CSIZE,
        max_iter=int(max_iter),
        tol=1e-4,
    )

    # 2) two-choice assignment of every row (device); host balance only
    # spills rows above the loose hard bound — routine cell-size variation
    # stays on the nearest centroid (see _BALANCE_HARD), the padded slot
    # count then follows the OBSERVED max list length
    d2_2, idx_2 = _assign_top2(
        jnp.asarray(X), centers, chunk=min(_ASSIGN_CHUNK, max(n, 1))
    )
    assign = _balanced_assign(
        np.asarray(d2_2), np.asarray(idx_2), nlist, hard_capacity(n, nlist)
    )
    max_len = int(np.bincount(assign, minlength=nlist).max())
    cap = -(-max(max_len, 1) // _CAP_MULTIPLE) * _CAP_MULTIPLE

    # 3) cluster-sorted CSR ordering, then scatter into the padded layout
    order = np.argsort(assign, kind="stable")
    lens = np.bincount(assign, minlength=nlist).astype(np.int32)
    offsets = np.zeros(nlist + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    sorted_assign = assign[order]
    pos_in_list = np.arange(n, dtype=np.int64) - offsets[sorted_assign]
    slots = sorted_assign * cap + pos_in_list
    grouped_x = np.zeros((nlist * cap, d), dtype=np.float32)
    grouped_x[slots] = X[order]
    grouped_sq = np.full((nlist * cap,), np.inf, dtype=np.float32)
    grouped_sq[slots] = (X[order] * X[order]).sum(axis=1)
    grouped_ids = np.full((nlist * cap,), -1, dtype=np.int32)
    grouped_ids[slots] = order.astype(np.int32)

    return IvfIndex(
        # host round-trip decommits the Lloyd output from the BUILD mesh so
        # the search-time mesh (possibly a different worker count) is free
        # to place every index array itself
        centroids=jnp.asarray(np.asarray(centers)),
        grouped_x=jnp.asarray(grouped_x),
        grouped_sq=jnp.asarray(grouped_sq),
        grouped_ids=jnp.asarray(grouped_ids),
        offsets=offsets,
        lens=lens,
        cap=cap,
        nlist=nlist,
        n_rows=n,
    )


# --------------------------------------------------------------------------
# probe search
# --------------------------------------------------------------------------


def _search_qchunk(cap: int, d: int) -> int:
    """Query chunk size bounding the (qc, cap, d) gathered candidate tile
    to ``_GATHER_BUDGET_ELEMS`` f32 elements (sublane-multiple)."""
    qc = _GATHER_BUDGET_ELEMS // max(cap * d, 1)
    qc = max(8, min(1024, qc))
    return max(8, (qc // 8) * 8)


def _probe_scan(
    Xq_l: jax.Array,
    cents: jax.Array,
    gx: jax.Array,
    gsq: jax.Array,
    gids: jax.Array,
    *,
    k: int,
    nprobe: int,
    cap: int,
    topk_impl: str,
    qchunk: int,
) -> Tuple[jax.Array, jax.Array]:
    """Per-device IVF search body: coarse top-nprobe, then a probe-major
    scan folding each (qc, cap) candidate window into a running top-k —
    the same raw-tile-then-2k-merge discipline as the exact ring's
    ``iblock`` (concatenating full tiles first costs an extra HBM
    materialization per probe). Lists are disjoint, so candidates never
    repeat across probes."""
    nq = Xq_l.shape[0]
    qc = min(qchunk, nq)
    pad = (-nq) % qc
    Xq_p = jnp.pad(Xq_l, ((0, pad), (0, 0)))
    c_sq = (cents * cents).sum(axis=1)
    cap_ar = jnp.arange(cap, dtype=jnp.int32)

    def qbody(_, xq):
        x_sq = (xq * xq).sum(axis=1)
        dc = pairwise_sq_dists(xq, cents, c_sq)  # (qc, nlist) MXU
        _, probes = lax.top_k(-dc, nprobe)       # (qc, nprobe)
        bd0 = jnp.full((qc, k), jnp.inf, Xq_l.dtype)
        bi0 = jnp.full((qc, k), -1, jnp.int32)

        def pstep(carry, pj):
            bd, bi = carry
            cand = pj[:, None] * cap + cap_ar[None, :]   # (qc, cap)
            xi = gx[cand]                                # (qc, cap, d)
            csq = gsq[cand]
            ids = gids[cand]
            dots = jnp.einsum("qd,qcd->qc", xq, xi)
            d2 = jnp.maximum(x_sq[:, None] - 2.0 * dots + csq, 0.0)
            if cap < k:
                # candidate window narrower than k: pad with +inf/-1 so
                # top_k stays legal and unfilled slots keep the convention
                d2 = jnp.pad(
                    d2, ((0, 0), (0, k - cap)), constant_values=jnp.inf
                )
                ids = jnp.pad(
                    ids, ((0, 0), (0, k - cap)), constant_values=-1
                )
            negd, sel = _tile_top_k(-d2, k, topk_impl)
            blk_ids = jnp.take_along_axis(ids, sel, axis=1)
            cat_d = jnp.concatenate([bd, -negd], axis=1)
            cat_i = jnp.concatenate([bi, blk_ids], axis=1)
            negm, selm = lax.top_k(-cat_d, k)
            return (-negm, jnp.take_along_axis(cat_i, selm, axis=1)), None

        (bd, bi), _ = lax.scan(
            pstep, (bd0, bi0), jnp.transpose(probes)  # (nprobe, qc)
        )
        return None, (bd, bi)

    _, (bd, bi) = lax.scan(
        qbody, None, Xq_p.reshape(-1, qc, Xq_l.shape[1])
    )
    return bd.reshape(-1, k)[:nq], bi.reshape(-1, k)[:nq]


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "k", "nprobe", "cap", "topk_impl", "qchunk"),
)
def _ivf_search_sharded(
    Xq: jax.Array,
    cents: jax.Array,
    gx: jax.Array,
    gsq: jax.Array,
    gids: jax.Array,
    *,
    mesh: Mesh,
    k: int,
    nprobe: int,
    cap: int,
    topk_impl: str,
    qchunk: int,
) -> Tuple[jax.Array, jax.Array]:
    body = functools.partial(
        _probe_scan,
        k=k, nprobe=nprobe, cap=cap, topk_impl=topk_impl, qchunk=qchunk,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(LAYOUT.rows(), LAYOUT.replicated(), LAYOUT.replicated(), LAYOUT.replicated(), LAYOUT.replicated()),
        out_specs=(LAYOUT.rows(), LAYOUT.rows()),
        check_vma=False,
    )(Xq, cents, gx, gsq, gids)


def _probe_scan_mp(
    Xq_l: jax.Array,
    cents: jax.Array,
    gx_l: jax.Array,
    gsq_l: jax.Array,
    gids_l: jax.Array,
    *,
    k: int,
    nprobe: int,
    cap: int,
    topk_impl: str,
    qchunk: int,
    n_local: int,
) -> Tuple[jax.Array, jax.Array]:
    """:func:`_probe_scan` with the grouped index LIST-SHARDED over mp.

    Each device holds only its own ``n_local = nlist_pad/mp`` lists
    (``LAYOUT.list_blocks()`` on dim 0 of the grouped arrays) — the index
    residency that bounds corpus size on a chip shrinks by 1/mp. The
    coarse quantizer stays replicated (it is (nlist, d) — small), so
    every mp peer ranks the same probe sequence; per probe only the
    OWNING shard gathers real candidates, the rest fold masked +inf/-1
    rows (a no-op on their running top-k). One all-gather of the per-shard
    (k) finalists per query chunk — O(mp·k) per row, never the candidate
    tiles — and a (qc, mp·k) top-k merge produce the global result.
    Probed lists are disjoint across shards, so the merged pool equals the
    replicated path's candidate pool exactly: recall is identical at equal
    nprobe (docs/mesh.md tolerance contract)."""
    from ..parallel.mesh import MP_AXIS

    nq = Xq_l.shape[0]
    qc = min(qchunk, nq)
    pad = (-nq) % qc
    Xq_p = jnp.pad(Xq_l, ((0, pad), (0, 0)))
    c_sq = (cents * cents).sum(axis=1)
    cap_ar = jnp.arange(cap, dtype=jnp.int32)
    l0 = lax.axis_index(MP_AXIS) * n_local     # first OWNED global list id

    def qbody(_, xq):
        x_sq = (xq * xq).sum(axis=1)
        dc = pairwise_sq_dists(xq, cents, c_sq)  # (qc, nlist) MXU
        _, probes = lax.top_k(-dc, nprobe)       # (qc, nprobe) global ids
        bd0 = jnp.full((qc, k), jnp.inf, Xq_l.dtype)
        bi0 = jnp.full((qc, k), -1, jnp.int32)

        def pstep(carry, pj):
            bd, bi = carry
            local = pj - l0                          # (qc,)
            own = (local >= 0) & (local < n_local)
            lc = jnp.clip(local, 0, n_local - 1)     # clamped: gather legal
            cand = lc[:, None] * cap + cap_ar[None, :]
            xi = gx_l[cand]                          # (qc, cap, d)
            csq = gsq_l[cand]
            ids = gids_l[cand]
            dots = jnp.einsum("qd,qcd->qc", xq, xi)
            d2 = jnp.maximum(x_sq[:, None] - 2.0 * dots + csq, 0.0)
            d2 = jnp.where(own[:, None], d2, jnp.inf)
            ids = jnp.where(own[:, None], ids, -1)
            if cap < k:
                d2 = jnp.pad(
                    d2, ((0, 0), (0, k - cap)), constant_values=jnp.inf
                )
                ids = jnp.pad(
                    ids, ((0, 0), (0, k - cap)), constant_values=-1
                )
            negd, sel = _tile_top_k(-d2, k, topk_impl)
            blk_ids = jnp.take_along_axis(ids, sel, axis=1)
            cat_d = jnp.concatenate([bd, -negd], axis=1)
            cat_i = jnp.concatenate([bi, blk_ids], axis=1)
            negm, selm = lax.top_k(-cat_d, k)
            return (-negm, jnp.take_along_axis(cat_i, selm, axis=1)), None

        (bd, bi), _ = lax.scan(
            pstep, (bd0, bi0), jnp.transpose(probes)
        )
        # 2k-style shard merge: every peer's k finalists, one all-gather
        abd = lax.all_gather(bd, MP_AXIS)            # (mp, qc, k)
        abi = lax.all_gather(bi, MP_AXIS)
        cat_d = jnp.moveaxis(abd, 0, 1).reshape(qc, -1)
        cat_i = jnp.moveaxis(abi, 0, 1).reshape(qc, -1)
        negm, selm = lax.top_k(-cat_d, k)
        return None, (-negm, jnp.take_along_axis(cat_i, selm, axis=1))

    _, (bd, bi) = lax.scan(
        qbody, None, Xq_p.reshape(-1, qc, Xq_l.shape[1])
    )
    return bd.reshape(-1, k)[:nq], bi.reshape(-1, k)[:nq]


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "k", "nprobe", "cap", "topk_impl", "qchunk", "n_local"
    ),
)
def _ivf_search_sharded_mp(
    Xq: jax.Array,
    cents: jax.Array,
    gx: jax.Array,
    gsq: jax.Array,
    gids: jax.Array,
    *,
    mesh: Mesh,
    k: int,
    nprobe: int,
    cap: int,
    topk_impl: str,
    qchunk: int,
    n_local: int,
) -> Tuple[jax.Array, jax.Array]:
    from ..parallel.mesh import MP_AXIS

    body = functools.partial(
        _probe_scan_mp,
        k=k, nprobe=nprobe, cap=cap, topk_impl=topk_impl, qchunk=qchunk,
        n_local=n_local,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(LAYOUT.rows(), LAYOUT.replicated(), LAYOUT.list_blocks(), LAYOUT.list_blocks(), LAYOUT.list_blocks()),
        out_specs=(LAYOUT.rows(), LAYOUT.rows()),
        check_vma=False,
    )(Xq, cents, gx, gsq, gids)


@functools.partial(
    jax.jit, static_argnames=("k", "nprobe", "cap", "topk_impl", "qchunk")
)
def _ivf_search_local(
    Xq: jax.Array,
    cents: jax.Array,
    gx: jax.Array,
    gsq: jax.Array,
    gids: jax.Array,
    *,
    k: int,
    nprobe: int,
    cap: int,
    topk_impl: str,
    qchunk: int,
) -> Tuple[jax.Array, jax.Array]:
    return _probe_scan(
        Xq, cents, gx, gsq, gids,
        k=k, nprobe=nprobe, cap=cap, topk_impl=topk_impl, qchunk=qchunk,
    )


# provenance of the most recent ivf_search dispatch (mirrors
# ops.streaming.last_ingest_report): callers read it AFTER the search to
# surface mp_degree / measured per-shard index bytes without threading a
# side channel through the return contract.
_LAST_SEARCH_REPORT: dict = {}


def last_search_report() -> dict:
    """Copy of the most recent :func:`ivf_search` dispatch provenance.
    Empty dict when the last search ran the replicated (1-D) layout."""
    return dict(_LAST_SEARCH_REPORT)


def ivf_search(
    Xq: jax.Array,
    index: IvfIndex,
    *,
    k: int,
    nprobe: int,
    topk_impl: str = "auto",
    mesh: Optional[Mesh] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Approximate k-NN against a built index.

    Returns ``(d2 (nq, k) ascending SQUARED distances, ids (nq, k) int32
    source-row ids)`` — the exact ring's output contract, so callers'
    sqrt/id-mapping plumbing is shared. With ``mesh`` the queries must be
    dp-sharded (``shard_rows``) and come back dp-sharded; without it the
    whole search runs on the default device (the single-host UMAP graph
    path, mirroring ``knn_brute``). ``topk_impl`` comes from
    ``resolve_knn_topk()`` — resolved by the caller outside jit.

    On a mesh with a model axis (and ``TPUML_MP_IVF`` on) the grouped
    index arrays are list-sharded over mp — lists padded to a multiple of
    mp with never-probed empty slots — and the probe scan runs
    :func:`_probe_scan_mp`; :func:`last_search_report` then carries
    ``mp_degree`` and the measured per-shard index bytes.
    """
    global _LAST_SEARCH_REPORT
    _LAST_SEARCH_REPORT = {}
    qchunk = _search_qchunk(index.cap, index.grouped_x.shape[1])
    if mesh is None:
        return _ivf_search_local(
            Xq, index.centroids, index.grouped_x, index.grouped_sq,
            index.grouped_ids,
            k=k, nprobe=nprobe, cap=index.cap, topk_impl=topk_impl,
            qchunk=qchunk,
        )
    n_mp = mp_ivf_shards(mesh, index.nlist)
    if n_mp > 1:
        cap = index.cap
        n_local = -(-index.nlist // n_mp)
        nlist_pad = n_local * n_mp
        pad_rows = (nlist_pad - index.nlist) * cap
        gx, gsq, gids = index.grouped_x, index.grouped_sq, index.grouped_ids
        if pad_rows:
            # empty pad lists: +inf ||x||² / -1 ids keep the slot
            # convention; their global list ids exceed nlist-1 so the
            # coarse quantizer can never rank them into a probe set
            gx = jnp.concatenate(
                [gx, jnp.zeros((pad_rows, gx.shape[1]), gx.dtype)]
            )
            gsq = jnp.concatenate(
                [gsq, jnp.full((pad_rows,), jnp.inf, gsq.dtype)]
            )
            gids = jnp.concatenate(
                [gids, jnp.full((pad_rows,), -1, gids.dtype)]
            )
        rep = NamedSharding(mesh, LAYOUT.replicated())
        blocks = NamedSharding(mesh, LAYOUT.list_blocks())
        cents = jax.device_put(index.centroids, rep)
        gx = jax.device_put(gx, blocks)
        gsq = jax.device_put(gsq, blocks)
        gids = jax.device_put(gids, blocks)
        _LAST_SEARCH_REPORT = {
            "mp_degree": n_mp,
            "index_shard_bytes": int(
                gx.addressable_shards[0].data.nbytes
                + gsq.addressable_shards[0].data.nbytes
                + gids.addressable_shards[0].data.nbytes
            ),
        }
        return _ivf_search_sharded_mp(
            Xq, cents, gx, gsq, gids,
            mesh=mesh, k=k, nprobe=nprobe, cap=cap, topk_impl=topk_impl,
            qchunk=qchunk, n_local=n_local,
        )
    # pin the (replicated) index operands to the SEARCH mesh: the build may
    # have committed them elsewhere, and jit refuses mixed device sets
    rep = NamedSharding(mesh, LAYOUT.replicated())
    cents, gx, gsq, gids = (
        jax.device_put(a, rep)
        for a in (
            index.centroids, index.grouped_x, index.grouped_sq,
            index.grouped_ids,
        )
    )
    return _ivf_search_sharded(
        Xq, cents, gx, gsq, gids,
        mesh=mesh, k=k, nprobe=nprobe, cap=index.cap, topk_impl=topk_impl,
        qchunk=qchunk,
    )
