"""Deterministic env-driven fault injection.

``TPUML_FAULT_SPEC`` is a comma-separated list of entries

    scope:point:index:action

where ``scope:point`` names an instrumented site (``ingest:chunk``,
``sgd:epoch``, ``gbt:round``, ``init:connect``, the serving plane's
``serve:admit`` / ``serve:dispatch`` / ``serve:transfer``, and the fit
scheduler's ``sched:admit`` / ``sched:preempt`` / ``sched:resume`` /
``sched:dispatch``), ``index`` is the 0-based hit count at that site on
which the fault fires, and ``action`` is one of

- ``raise``   — raise :class:`InjectedFault` (a generic hard error),
- ``preempt`` — raise :class:`SimulatedPreemption` (terminal: the retry
                wrapper never swallows it, modeling a pod preemption that
                kills the process; recovery is refit-from-checkpoint),
- ``oom``     — raise :class:`InjectedResourceExhausted` (its message
                contains ``RESOURCE_EXHAUSTED`` so it takes the staging
                chunk-halving path).

Each entry fires exactly once: after firing it is spent, so an in-process
retry or refit sails past the site. Hit counters are per-site and
monotonically increase for the life of the injector; :func:`reset_faults`
rebuilds the injector (tests call it between scenarios).

With ``TPUML_FAULT_SPEC`` unset every hook is a no-op costing one dict
lookup — the production path stays inert.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from . import envspec, lockwitness

SITES = (
    "ingest:chunk", "sgd:epoch", "init:connect",
    # serving plane (hit per admission attempt / group dispatch /
    # device->host result fetch — see serving/runtime.py)
    "serve:admit", "serve:dispatch", "serve:transfer",
    # GBT boosting round boundary (models/tree.py) — the per-round
    # twin of sgd:epoch, so an interrupted-then-resumed GBT fit is
    # testable the same way the SGD solvers are
    "gbt:round",
    # fit scheduler (hit per job submit / quantum yield / resumed
    # re-dispatch / job dispatch — see runtime/scheduler.py)
    "sched:admit", "sched:preempt", "sched:resume", "sched:dispatch",
    # hot-swap lifecycle (hit before the staged ladder warmup / before
    # the atomic routing flip — see serving/registry.py): a fault at
    # either site must leave the prior version serving untouched
    "swap:warm", "swap:flip",
)
ACTIONS = ("raise", "preempt", "oom")


class FaultSpecError(ValueError):
    """Malformed ``TPUML_FAULT_SPEC`` value."""


class InjectedFault(RuntimeError):
    """Generic injected failure (``raise`` action)."""


class SimulatedPreemption(RuntimeError):
    """Injected preemption (``preempt`` action).

    Terminal by contract: ``with_retries`` re-raises it without retrying,
    the same way a real preemption is not survivable in-process.
    """


class InjectedResourceExhausted(RuntimeError):
    """Injected allocator failure (``oom`` action).

    The message embeds ``RESOURCE_EXHAUSTED`` so
    :func:`spark_rapids_ml_tpu.runtime.retry.is_resource_exhausted`
    classifies it exactly like a real XLA staging OOM.
    """

    def __init__(self, site: str) -> None:
        super().__init__(f"RESOURCE_EXHAUSTED: injected at {site}")


def parse_fault_spec(spec: str) -> List[Tuple[str, int, str]]:
    """Parse ``TPUML_FAULT_SPEC`` into ``[(site, index, action), ...]``."""
    entries: List[Tuple[str, int, str]] = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) != 4:
            raise FaultSpecError(
                f"TPUML_FAULT_SPEC entry {raw!r} is not scope:point:index:action"
            )
        scope, point, idx_s, action = (p.strip() for p in parts)
        site = f"{scope}:{point}"
        if site not in SITES:
            raise FaultSpecError(
                f"TPUML_FAULT_SPEC entry {raw!r}: unknown site {site!r} "
                f"(expected one of {', '.join(SITES)})"
            )
        if action not in ACTIONS:
            raise FaultSpecError(
                f"TPUML_FAULT_SPEC entry {raw!r}: unknown action {action!r} "
                f"(expected one of {', '.join(ACTIONS)})"
            )
        try:
            idx = int(idx_s)
        except ValueError:
            raise FaultSpecError(
                f"TPUML_FAULT_SPEC entry {raw!r}: index {idx_s!r} is not an integer"
            ) from None
        if idx < 0:
            raise FaultSpecError(
                f"TPUML_FAULT_SPEC entry {raw!r}: index must be >= 0"
            )
        entries.append((site, idx, action))
    return entries


class FaultInjector:
    """Deterministic chaos hooks driven by a parsed fault spec."""

    def __init__(self, spec: str) -> None:
        self.spec = spec
        self._lock = lockwitness.make_lock("faults.plan")
        self._hits: Dict[str, int] = {}
        # site -> {index: action}; later entries for the same (site, index)
        # win, matching "last setting wins" env semantics.
        self._pending: Dict[str, Dict[int, str]] = {}
        for site, idx, action in parse_fault_spec(spec):
            self._pending.setdefault(site, {})[idx] = action

    def active_sites(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(s for s, m in self._pending.items() if m)

    def hit(self, site: str) -> None:
        """Record one pass through ``site``; raise if a fault is due."""
        with self._lock:
            n = self._hits.get(site, 0)
            self._hits[site] = n + 1
            action = self._pending.get(site, {}).pop(n, None)
        if action is None:
            return
        try:
            from . import telemetry

            telemetry.counter("fault_injections").inc(kind=action)
            telemetry.add_span_event(
                "fault_injected", site=site, index=n, action=action
            )
        except Exception:  # pragma: no cover - tracing must not mask the fault
            pass
        if action == "preempt":
            raise SimulatedPreemption(f"injected preemption at {site}[{n}]")
        if action == "oom":
            raise InjectedResourceExhausted(f"{site}[{n}]")
        raise InjectedFault(f"injected fault at {site}[{n}]")


_cache_lock = lockwitness.make_lock("faults.cache")
_cached: Optional[Tuple[str, Optional[FaultInjector]]] = None


def _injector() -> Optional[FaultInjector]:
    global _cached
    spec = envspec.get("TPUML_FAULT_SPEC")
    with _cache_lock:
        if _cached is not None and _cached[0] == spec:
            return _cached[1]
        inj = FaultInjector(spec) if spec else None
        _cached = (spec, inj)
        return inj


def fault_site(site: str) -> None:
    """Instrumentation hook: call at every pass through ``site``.

    No-op (one env read + cache hit) unless ``TPUML_FAULT_SPEC`` names a
    pending fault for this site at the current hit index.
    """
    inj = _injector()
    if inj is not None:
        inj.hit(site)


def fault_sites_active(*sites: str) -> bool:
    """True when any of ``sites`` still has an unfired fault entry."""
    inj = _injector()
    if inj is None:
        return False
    active = inj.active_sites()
    return any(s in active for s in sites)


def reset_faults() -> None:
    """Forget fired entries and hit counts (rebuilds from current env)."""
    global _cached
    with _cache_lock:
        _cached = None
