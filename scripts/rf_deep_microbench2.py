"""Follow-up RF measurements: unfoldable scatter dependence + full-tree
ground truth + bf16 Pallas variant.

The first microbench's scatter-level loop dependence (`+ c % 1`) was
constant-foldable, letting XLA hoist the scatter out of the timing loop.
This run uses a data-dependent select XLA cannot fold.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

ITERS = 16

N = 131072
K = 16
NB = 128
S = 2
N_NODES = 4096


def timeit_looped(jitted, *args, reps=3, warmup=1, iters=ITERS):
    for _ in range(warmup):
        np.asarray(jnp.ravel(jitted(*args))[:1])
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jnp.ravel(jitted(*args))[:1])
        ts.append(time.perf_counter() - t0)
    return min(ts) / iters


def main():
    print("devices:", jax.devices())
    rng = np.random.default_rng(0)
    binc = jnp.asarray(rng.integers(0, NB, size=(N, K)), jnp.int32)
    sw = jnp.asarray(rng.random((N, S)), jnp.float32)
    local = jnp.asarray(rng.integers(0, N_NODES, size=(N,)), jnp.int32)

    # 1. scatter level with unfoldable dependence: where(c >= 0, binc, 0)
    #    costs one select pass (~0.1 ms) but cannot be hoisted.
    @jax.jit
    def hist_scatter_loop(binc, local, sw):
        def body(_, c):
            b2 = jnp.where(c >= 0.0, binc, 0)
            ids = local[:, None] * NB + b2
            hist = jnp.stack(
                [
                    jax.vmap(
                        lambda col, cc=sw[:, s]: jax.ops.segment_sum(
                            cc, col, num_segments=N_NODES * NB + 1
                        ),
                        in_axes=1,
                    )(ids)
                    for s in range(S)
                ],
                axis=-1,
            )
            return hist[:, : N_NODES * NB, :].sum()

        return lax.fori_loop(0, ITERS, body, jnp.float32(0.0))

    t = timeit_looped(hist_scatter_loop, binc, local, sw)
    print(f"1. scatter level UNFOLDABLE (n={N}, k={K}): {t*1e3:.2f} ms "
          f"({N*K*S/t/1e8:.2f}e8 upd/s)")

    # 1b. same at shallow width (n_nodes=8): is scatter node-count-flat?
    local8 = jnp.asarray(rng.integers(0, 8, size=(N,)), jnp.int32)

    @jax.jit
    def hist_scatter8(binc, local, sw):
        def body(_, c):
            b2 = jnp.where(c >= 0.0, binc, 0)
            ids = local[:, None] * NB + b2
            hist = jnp.stack(
                [
                    jax.vmap(
                        lambda col, cc=sw[:, s]: jax.ops.segment_sum(
                            cc, col, num_segments=8 * NB + 1
                        ),
                        in_axes=1,
                    )(ids)
                    for s in range(S)
                ],
                axis=-1,
            )
            return hist[:, : 8 * NB, :].sum()

        return lax.fori_loop(0, ITERS, body, jnp.float32(0.0))

    t = timeit_looped(hist_scatter8, binc, local8, sw)
    print(f"1b. scatter level n_nodes=8: {t*1e3:.2f} ms "
          f"({N*K*S/t/1e8:.2f}e8 upd/s)")

    # 2. full current-code tree build at bench shape (ground truth)
    from spark_rapids_ml_tpu.ops.tree_kernels import (
        ForestConfig, _build_tree, next_pow2,
    )

    d = 256
    bins = jnp.asarray(rng.integers(0, NB, size=(N, d)), jnp.uint8)
    stats = jnp.asarray(
        np.stack([rng.random(N), rng.random(N)], axis=1), jnp.float32
    )
    valid = jnp.ones((N,), jnp.float32)
    cfg = ForestConfig(
        max_depth=13, n_bins=NB, n_features=d, n_stats=S,
        impurity="gini", k_features=16, min_samples_leaf=1,
        min_info_gain=0.0, min_samples_split=2, bootstrap=True,
        hist_strategy="auto", contract_gather="auto",
    )

    @jax.jit
    def one_tree(bins, stats, valid, key):
        out = _build_tree(bins, stats, valid, key, cfg)
        return out["leaf_stats"].sum() + out["gain"].sum()

    key = jax.random.PRNGKey(0)
    t = timeit_looped(one_tree, bins, stats, valid, key, iters=1, reps=3)
    print(f"2. full _build_tree depth13 (current code): {t*1e3:.1f} ms")

    # 3. Pallas kernel bf16 variant comparison is deferred; re-measure f32
    #    with the select-guard to match methodology
    from spark_rapids_ml_tpu.ops.rf_pallas import subblock_hist

    binq = jnp.asarray(rng.integers(0, NB, size=(N, K)), jnp.int32)
    swq = jnp.asarray(rng.random((N, S)), jnp.float32)

    for r_sub in (8, 16):
        @jax.jit
        def phist_loop(binq, swq):
            def body(_, c):
                b2 = jnp.where(c >= 0.0, binq, 0)
                h = subblock_hist(b2, swq, n_bins=NB, r_sub=r_sub)
                return h.sum()

            return lax.fori_loop(0, ITERS, body, jnp.float32(0.0))

        t = timeit_looped(phist_loop, binq, swq)
        print(f"3. pallas subblock hist guarded (r_sub={r_sub}): {t*1e3:.2f} ms")


if __name__ == "__main__":
    main()
