"""Drop-in module alias: ``spark_rapids_ml_tpu.regression`` ≙ reference
``spark_rapids_ml.regression`` (``/root/reference/python/src/spark_rapids_ml/regression.py``)."""

from .models.regression import LinearRegression, LinearRegressionModel
from .models.tree import (
    GBTRegressionModel,
    GBTRegressor,
    RandomForestRegressionModel,
    RandomForestRegressor,
)

__all__ = [
    "GBTRegressor",
    "GBTRegressionModel",
    "LinearRegression",
    "LinearRegressionModel",
    "RandomForestRegressor",
    "RandomForestRegressionModel",
]
