"""pyspark.ml API-shape parity.

Two tiers (round-5 structure, per the round-4 verdict):

* FIXTURE tier — always runs, pyspark or not. The pyspark Param surfaces
  and defaults are pinned in ``tests/fixtures/pyspark_param_defaults.json``
  (Spark 3.5.x), and a Spark-physical-schema VectorUDT parquet directory
  (mixed dense/sparse rows + array<float> + label, Spark row-metadata key,
  part-file + _SUCCESS layout) is checked in under
  ``tests/fixtures/spark_vectorudt_parquet`` with its dense expansion in
  ``spark_vectorudt_expected.npy`` (generator: ``gen_spark_fixture.py``).

* LIVE tier — runs only where pyspark is installed: the same assertions
  against the genuine ``pyspark.ml`` classes and genuinely Spark-written
  files, so API drift in a NEW Spark release fails there first.
"""

import json
import os

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import (
    LogisticRegression,
    RandomForestClassifier,
)
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.regression import (
    LinearRegression,
    RandomForestRegressor,
)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")
OURS = {
    "PCA": PCA,
    "KMeans": KMeans,
    "LinearRegression": LinearRegression,
    "LogisticRegression": LogisticRegression,
    "RandomForestClassifier": RandomForestClassifier,
    "RandomForestRegressor": RandomForestRegressor,
}

with open(os.path.join(FIXTURES, "pyspark_param_defaults.json")) as f:
    _TABLE = {k: v for k, v in json.load(f).items() if not k.startswith("_")}


# --------------------------------------------------------------------------
# fixture tier (always runs)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(_TABLE))
def test_fixture_spark_params_are_accepted(name):
    """Every Param pyspark.ml exposes (pinned table) must be accepted by
    our estimator — mapped, or accepted-and-ignored, never an
    unknown-attribute surprise."""
    our_est = OURS[name]()
    mapping = getattr(type(our_est), "_param_mapping", lambda: {})()
    for pname in _TABLE[name]["params"]:
        assert our_est.hasParam(pname) or pname in mapping, (
            f"{name} silently lacks Spark param {pname!r}"
        )


@pytest.mark.parametrize("name", sorted(_TABLE))
def test_fixture_spark_defaults_match(name):
    """Shared Params must carry Spark's default values (the drop-in
    contract: constructing with no arguments behaves identically)."""
    our_est = OURS[name]()
    for pname, sv in _TABLE[name]["defaults"].items():
        if not our_est.hasParam(pname):
            continue
        p = our_est.getParam(pname)
        if not our_est.hasDefault(p):
            continue
        ov = our_est.getOrDefault(p)
        if isinstance(sv, float):
            assert ov == pytest.approx(sv), f"{name}.{pname}"
        else:
            assert ov == sv, f"{name}.{pname}"


def test_fixture_vectorudt_parquet_roundtrip():
    """The checked-in Spark-physical-schema parquet (mixed dense/sparse
    VectorUDT + array<float> + label, Spark directory layout) must load
    through our DataFrame with the exact dense expansion — the on-disk
    interop contract data/dataframe.py implements (reference consumes it
    via Spark itself, core.py:160-241)."""
    path = os.path.join(FIXTURES, "spark_vectorudt_parquet")
    expect = np.load(os.path.join(FIXTURES, "spark_vectorudt_expected.npy"))
    df = DataFrame.scan_parquet(path)
    X = np.asarray(df.column("features"))
    np.testing.assert_allclose(X, expect, rtol=0, atol=0)
    extra = np.asarray(df.column("extra"))
    n = expect.shape[0]
    np.testing.assert_allclose(extra[:, 0], np.arange(n, dtype=np.float64))
    np.testing.assert_allclose(extra[:, 1], 2.0 * np.arange(n))
    y = np.asarray(df.column("label"))
    np.testing.assert_allclose(y, np.arange(n) % 2)


def test_fixture_vectorudt_fit_end_to_end():
    """The fixture data must flow through a real estimator fit — the
    loader's output is consumed by the library, not just shape-checked."""
    path = os.path.join(FIXTURES, "spark_vectorudt_parquet")
    df = DataFrame.scan_parquet(path)
    model = PCA(k=2, inputCol="features", outputCol="pca").fit(df)
    out = model.transform(df)
    assert np.asarray(out["pca"]).shape[1] == 2


# --------------------------------------------------------------------------
# live tier (requires pyspark)
# --------------------------------------------------------------------------


def _spark_pairs():
    from pyspark.ml.classification import (
        LogisticRegression as SparkLogReg,
        RandomForestClassifier as SparkRFC,
    )
    from pyspark.ml.clustering import KMeans as SparkKMeans
    from pyspark.ml.feature import PCA as SparkPCA
    from pyspark.ml.regression import (
        LinearRegression as SparkLinReg,
        RandomForestRegressor as SparkRFR,
    )

    return [
        (PCA, SparkPCA),
        (KMeans, SparkKMeans),
        (LinearRegression, SparkLinReg),
        (LogisticRegression, SparkLogReg),
        (RandomForestClassifier, SparkRFC),
        (RandomForestRegressor, SparkRFR),
    ]


@pytest.fixture(scope="module")
def spark():
    """pyspark.ml estimators are JavaEstimator wrappers whose __init__
    requires an active SparkContext."""
    pytest.importorskip("pyspark")
    from pyspark.sql import SparkSession

    session = SparkSession.builder.master("local[1]").getOrCreate()
    yield session
    session.stop()


def test_live_spark_params_are_accepted(spark):
    for ours, theirs in _spark_pairs():
        spark_est = theirs()
        our_est = ours()
        mapping = getattr(ours, "_param_mapping", lambda: {})()
        for p in spark_est.params:
            assert our_est.hasParam(p.name) or p.name in mapping, (
                f"{ours.__name__} silently lacks Spark param {p.name!r}"
            )


def test_live_spark_defaults_match(spark):
    for ours, theirs in _spark_pairs():
        spark_est = theirs()
        our_est = ours()
        for p in spark_est.params:
            if not (spark_est.hasDefault(p) and our_est.hasParam(p.name)):
                continue
            ours_p = our_est.getParam(p.name)
            if not our_est.hasDefault(ours_p):
                continue
            sv = spark_est.getOrDefault(p)
            ov = our_est.getOrDefault(ours_p)
            if isinstance(sv, float):
                assert ov == pytest.approx(sv), p.name
            else:
                assert ov == sv, p.name


def test_live_vectorudt_parquet_roundtrip(tmp_path, spark):
    """A genuinely Spark-written VectorUDT parquet must load through our
    DataFrame with identical, row-aligned values."""
    from pyspark.ml.linalg import Vectors

    rows = [
        (Vectors.dense([float(i), float(i) / 2]), float(i % 2))
        for i in range(64)
    ]
    sdf = spark.createDataFrame(rows, ["features", "label"])
    path = str(tmp_path / "vec.parquet")
    sdf.write.parquet(path)
    df = DataFrame.scan_parquet(path)
    X = np.asarray(df.column("features"))
    y = np.asarray(df.column("label"))
    assert X.shape == (64, 2)
    order = np.argsort(X[:, 0])
    np.testing.assert_allclose(X[order, 0], np.arange(64.0))
    np.testing.assert_allclose(X[order, 1], np.arange(64.0) / 2)
    np.testing.assert_allclose(y[order], np.arange(64) % 2)
