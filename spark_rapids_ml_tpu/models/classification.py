"""Classification estimators — Spark ML drop-ins, TPU-native fit/transform.

LogisticRegression reference:
``/root/reference/python/src/spark_rapids_ml/classification.py:651-1562``.
Param-mapping parity (reference ``classification.py:652-671``):
``maxIter→max_iter``, ``regParam→C`` (value-mapped 1/x), ``elasticNetParam→
l1_ratio``, ``tol→tol``, ``fitIntercept→fit_intercept``, ``standardization→
standardization``, ``family`` accepted-but-ignored (auto-detected),
``threshold``/``thresholds``/``weightCol``/``aggregationDepth``/coefficient
bounds unsupported (raise on set).

Fit is the jitted distributed L-BFGS/OWL-QN in ``ops/logreg_kernels.py``.
``fitMultiple`` reuses the device-resident design matrix for every param map
(reference single-pass loop ``classification.py:1137-1154``); ``_combine``
stacks models for single-pass CV evaluation (``classification.py:1504-1519``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core import FitFunc, FitInputs, _TpuEstimatorSupervised, _TpuModel
from ..data.dataframe import DataFrame
from ..params import (
    HasElasticNetParam,
    HasEnableSparseDataOptim,
    HasFeaturesCol,
    HasFeaturesCols,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasRegParam,
    HasStandardization,
    HasTol,
    TypeConverters,
    _mk,
)
from ..ops.logreg_kernels import logreg_fit, logreg_fit_batched, logreg_predict
from ..runtime import envspec
from ..utils.logging import get_logger


def _resolve_objective_dtype(params: Dict[str, Any]) -> str:
    """Validated objective dtype from the kwarg or env (empty string means
    unset; typos error rather than silently running f32)."""
    v = (
        params.get("objective_dtype")
        or envspec.get("TPUML_LOGREG_OBJECTIVE_DTYPE")
    )
    v = str(v)
    if v not in ("float32", "bfloat16"):
        raise ValueError(
            f"objective_dtype must be float32|bfloat16, got {v!r}"
        )
    return v


class LogisticRegressionClass:
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # reference ``classification.py:652-671``
        return {
            "maxIter": "max_iter",
            "regParam": "C",
            "elasticNetParam": "l1_ratio",
            "tol": "tol",
            "fitIntercept": "fit_intercept",
            "threshold": None,
            "thresholds": None,
            "standardization": "standardization",
            "weightCol": None,
            "aggregationDepth": None,
            "family": "",
            "lowerBoundsOnCoefficients": None,
            "upperBoundsOnCoefficients": None,
            "lowerBoundsOnIntercepts": None,
            "upperBoundsOnIntercepts": None,
            "maxBlockSizeInMB": None,
        }

    @classmethod
    def _param_value_mapping(cls) -> Dict[str, Callable[[Any], Any]]:
        # Spark regParam -> inverse-regularization C (reference
        # ``classification.py:676-678``); C=0 encodes "no penalty"
        def _c(x: float) -> float:
            if x > 0.0:
                return 1.0 / x
            if x == 0.0:
                return 0.0
            raise ValueError(f"regParam must be >= 0, got {x}")

        return {"C": _c}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "fit_intercept": True,
            "standardization": True,
            "C": 0.0,
            "l1_ratio": 0.0,
            "max_iter": 100,
            "tol": 1e-6,
            "objective_dtype": None,
        }


class _LogisticRegressionParams(
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasMaxIter,
    HasTol,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
    HasStandardization,
    HasEnableSparseDataOptim,
):
    family = _mk(
        "family", "binomial | multinomial | auto (auto-detected)", TypeConverters.toString
    )
    threshold = _mk("threshold", "binary prediction threshold (unsupported)", TypeConverters.toFloat)
    thresholds = _mk("thresholds", "per-class thresholds (unsupported)", TypeConverters.toListFloat)
    weightCol = _mk("weightCol", "weight column (unsupported)", TypeConverters.toString)
    aggregationDepth = _mk("aggregationDepth", "tree aggregate depth (unsupported)", TypeConverters.toInt)
    maxBlockSizeInMB = _mk("maxBlockSizeInMB", "block size hint (unsupported)", TypeConverters.toFloat)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            maxIter=100,
            regParam=0.0,
            elasticNetParam=0.0,
            tol=1e-6,
            family="auto",
        )

    def getFamily(self) -> str:
        return self.getOrDefault("family")


class LogisticRegression(
    LogisticRegressionClass, _TpuEstimatorSupervised, _LogisticRegressionParams
):
    """``LogisticRegression(regParam=0.01).fit(df)`` — drop-in for
    ``pyspark.ml.classification.LogisticRegression``. Labels must be
    non-negative integers (reference ``classification.py:1103-1112``)."""

    def __init__(self, **kwargs: Any) -> None:
        _TpuEstimatorSupervised.__init__(self)
        _LogisticRegressionParams.__init__(self)
        self._set_params(**kwargs)

    def setMaxIter(self, value: int) -> "LogisticRegression":
        self._set_params(maxIter=value)
        return self

    def setRegParam(self, value: float) -> "LogisticRegression":
        self._set_params(regParam=value)
        return self

    def setElasticNetParam(self, value: float) -> "LogisticRegression":
        self._set_params(elasticNetParam=value)
        return self

    def setTol(self, value: float) -> "LogisticRegression":
        self._set_params(tol=value)
        return self

    def setFitIntercept(self, value: bool) -> "LogisticRegression":
        self._set_params(fitIntercept=value)
        return self

    def setStandardization(self, value: bool) -> "LogisticRegression":
        self._set_params(standardization=value)
        return self

    def setProbabilityCol(self, value: str) -> "LogisticRegression":
        self._set_params(probabilityCol=value)
        return self

    def setRawPredictionCol(self, value: str) -> "LogisticRegression":
        self._set_params(rawPredictionCol=value)
        return self

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        return True

    def _x_placement_dtype(self):
        """bf16 objective reads start at placement: X goes to device in
        bf16 (half the H2D bytes, zero-copy inside ``logreg_fit``) instead
        of being converted in-program, which would hold the f32 argument
        AND the bf16 copy live (OOM at near-HBM scales). Resolved from the
        ESTIMATOR-level setting: fitMultiple param maps share one placed X,
        so a per-map override cannot re-place it (a map asking f32 over a
        bf16-placed X still reads bf16 — solver state is f32 either way).
        Whether placement actually applies is core's decision: it narrows
        only when the RESOLVED input dtype is f32 (so f64 compat fits are
        never silently rounded), which covers float32_inputs=False over
        f32 data too."""
        import jax.numpy as jnp

        if _resolve_objective_dtype(self._tpu_params) == "bfloat16":
            return jnp.bfloat16
        return None

    def _supportsTransformEvaluate(self, evaluator: Any) -> bool:
        from ..evaluation import MulticlassClassificationEvaluator

        return isinstance(evaluator, MulticlassClassificationEvaluator)

    def _get_tpu_fit_func(self, dataset: DataFrame) -> FitFunc:
        # label analysis happens on host, once, outside jit (the class count
        # is a static shape parameter of the compiled program). It must be
        # GLOBAL: in a multi-process world each rank sees only its
        # partition, and ranks disagreeing on n_classes (or on the
        # degenerate single-label early-return) would compile different
        # collectives and deadlock.
        from ..parallel.mesh import global_label_summary

        label_col = self.getOrDefault("labelCol")
        ls = global_label_summary(np.asarray(dataset.column(label_col)))
        if ls["total"] == 0:
            raise ValueError("Labels column is empty")
        if ls["y_min"] < 0 or not ls["all_int"]:
            raise RuntimeError(
                "Labels MUST be non-negative integers, got values outside that set"
            )
        # Spark semantics: numClasses = max(label) + 1
        n_classes = max(int(ls["y_max"]) + 1, 2)
        single_label = ls["all_same"]
        single_label_val = ls["first"]

        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            multinomial = n_classes > 2
            fit_intercept = bool(params["fit_intercept"])

            if single_label and n_classes == 2:
                # single-label degenerate case (reference
                # ``classification.py:1119-1132``): all-0 or all-1 labels
                class_val = single_label_val
                if fit_intercept:
                    return {
                        "coef_": np.zeros((1, inputs.n_features)),
                        "intercept_": np.asarray(
                            [np.inf if class_val == 1.0 else -np.inf]
                        ),
                        "n_classes": n_classes,
                        "multinomial": False,
                        "n_iter": 0,
                        "objective": 0.0,
                    }

            c = float(params["C"])
            reg = 1.0 / c if c > 0.0 else 0.0
            l1_ratio = float(params["l1_ratio"])
            out = logreg_fit(
                inputs.X,
                inputs.mask,
                inputs.y,
                n_classes=n_classes,
                multinomial=multinomial,
                fit_intercept=fit_intercept,
                standardization=bool(params["standardization"]),
                l1=jnp.asarray(reg * l1_ratio, inputs.dtype),
                l2=jnp.asarray(reg * (1.0 - l1_ratio), inputs.dtype),
                use_l1=reg * l1_ratio > 0.0,
                max_iter=int(params["max_iter"]),
                tol=jnp.asarray(float(params["tol"]), inputs.dtype),
                # rows are dp-sharded by _pre_process_data: lets the TPU
                # path use the fused Pallas loss+grad pass
                mesh=inputs.mesh,
                # bf16 objective reads (f32 accumulation) via framework
                # kwarg or env; default full f32
                objective_dtype=_resolve_objective_dtype(params),
            )
            return {
                "coef_": np.asarray(out["coef_"]),
                "intercept_": np.asarray(out["intercept_"]),
                "n_classes": n_classes,
                "multinomial": multinomial,
                "n_iter": int(out["n_iter"]),
                "objective": float(out["objective"]),
            }

        return _fit

    # ---- gang-fit path ---------------------------------------------------
    @staticmethod
    def _gang_reg_pair(ps: Dict[str, Any]) -> Tuple[float, float]:
        """Per-lane (l1, l2) strengths from the stored C/l1_ratio params —
        the same arithmetic the solo ``_fit`` uses."""
        c = float(ps["C"])
        reg = 1.0 / c if c > 0.0 else 0.0
        l1_ratio = float(ps["l1_ratio"])
        return reg * l1_ratio, reg * (1.0 - l1_ratio)

    def _gang_fit_groups(
        self, param_sets: List[Dict[str, Any]]
    ) -> Optional[List[Tuple[Any, List[int]]]]:
        # static kernel params split buckets; l1/l2/tol ride traced (B,)
        # arrays. use_l1 is static on purpose: OWL-QN's direction sign-fix
        # and orthant projection are NOT identities at l1=0, so plain and
        # OWL-QN lanes compile different programs.
        groups: Dict[Tuple[Any, ...], List[int]] = {}
        for i, ps in enumerate(param_sets):
            l1, _ = self._gang_reg_pair(ps)
            key = (
                bool(ps["fit_intercept"]),
                bool(ps["standardization"]),
                l1 > 0.0,
                int(ps["max_iter"]),
                _resolve_objective_dtype(ps),
            )
            groups.setdefault(key, []).append(i)
        return list(groups.items())

    def _gang_fit_supports_folds(self) -> bool:
        return True

    def _gang_lane_bytes(self, inputs: FitInputs) -> float:
        # dominated by the (n, B, K) logits block and its backward twin:
        # ~4 such f32 temporaries live per objective evaluation
        k_eff = float(getattr(self, "_gang_k_eff", 1))
        return 16.0 * float(inputs.X.shape[0]) * k_eff

    def _get_tpu_gang_fit_func(self, dataset: DataFrame):
        from ..parallel.mesh import global_label_summary

        label_col = self.getOrDefault("labelCol")
        ls = global_label_summary(np.asarray(dataset.column(label_col)))
        if ls["total"] == 0 or ls["y_min"] < 0 or not ls["all_int"]:
            return None  # solo path raises the user-facing error
        if ls["all_same"]:
            # degenerate single-label fits bypass the solver entirely
            return None
        n_classes = max(int(ls["y_max"]) + 1, 2)
        multinomial = n_classes > 2
        self._gang_k_eff = n_classes if multinomial else 1

        def _gang_fit(
            inputs: FitInputs,
            group_ps: List[Dict[str, Any]],
            *,
            fold_id: Any = None,
            lane_fold: Any = None,
            n_folds: int = 0,
        ) -> List[Dict[str, Any]]:
            ps0 = group_ps[0]
            pairs = [self._gang_reg_pair(ps) for ps in group_ps]
            l1 = jnp.asarray([p[0] for p in pairs], inputs.dtype)
            l2 = jnp.asarray([p[1] for p in pairs], inputs.dtype)
            tol = jnp.asarray([float(ps["tol"]) for ps in group_ps], inputs.dtype)
            out = logreg_fit_batched(
                inputs.X,
                inputs.mask,
                inputs.y,
                n_classes=n_classes,
                multinomial=multinomial,
                fit_intercept=bool(ps0["fit_intercept"]),
                standardization=bool(ps0["standardization"]),
                l1=l1,
                l2=l2,
                use_l1=bool(pairs[0][0] > 0.0),
                max_iter=int(ps0["max_iter"]),
                tol=tol,
                mesh=inputs.mesh,
                objective_dtype=_resolve_objective_dtype(ps0),
                fold_id=fold_id,
                lane_fold=(
                    None if lane_fold is None else jnp.asarray(lane_fold, jnp.int32)
                ),
                n_folds=int(n_folds),
            )
            coef = np.asarray(out["coef_"])
            intercept = np.asarray(out["intercept_"])
            n_iter = np.asarray(out["n_iter"])
            objective = np.asarray(out["objective"])
            return [
                {
                    "coef_": coef[b],
                    "intercept_": intercept[b],
                    "n_classes": n_classes,
                    "multinomial": multinomial,
                    "n_iter": int(n_iter[b]),
                    "objective": float(objective[b]),
                }
                for b in range(len(group_ps))
            ]

        return _gang_fit

    def _get_tpu_streaming_fit_func(self, dataset: DataFrame):
        """Out-of-core fit: host-driven L-BFGS/OWL-QN where every objective
        evaluation is one chunked pass over the data (the re-read-per-
        iteration cost cuML's out-of-core QN pays, reference
        ``classification.py:955-1140``); label analysis is its own streaming
        pass instead of a column materialization."""
        from ..core import StreamInputs
        from ..ops.streaming import streamed_label_stats, streamed_logreg_fit

        label_cache: Dict[str, Any] = {}

        def _fit(inputs: StreamInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            if not label_cache:
                label_cache.update(
                    streamed_label_stats(inputs.source, inputs.chunk_rows)
                )
            ls = label_cache
            if ls["y_min"] < 0 or not ls["all_int"]:
                raise RuntimeError(
                    "Labels MUST be non-negative integers, got values outside that set"
                )
            # Spark semantics: numClasses = max(label) + 1
            n_classes = max(int(ls["y_max"]) + 1, 2)
            multinomial = n_classes > 2
            fit_intercept = bool(params["fit_intercept"])

            if ls["all_same"] and n_classes == 2 and fit_intercept:
                # single-label degenerate case (reference
                # ``classification.py:1119-1132``)
                class_val = float(ls["first"])
                return {
                    "coef_": np.zeros((1, inputs.n_features)),
                    "intercept_": np.asarray(
                        [np.inf if class_val == 1.0 else -np.inf]
                    ),
                    "n_classes": n_classes,
                    "multinomial": False,
                    "n_iter": 0,
                    "objective": 0.0,
                }

            c = float(params["C"])
            reg = 1.0 / c if c > 0.0 else 0.0
            l1_ratio = float(params["l1_ratio"])
            if _resolve_objective_dtype(params) != "float32":
                # validate AND be explicit: the streamed fit's bottleneck
                # is chunk ingest (the wire-dtype path already narrows
                # transfers), so bf16 objective reads do not apply here
                get_logger(type(self)).warning(
                    "objective_dtype=bfloat16 applies to the resident fit "
                    "only; the streaming fit reads chunks at wire dtype"
                )
            # checkpoint identity: the L-BFGS walk is fully determined by
            # the objective config + data; shape/size stand in for a data
            # digest (a content pass would cost a full extra read)
            from ..runtime.checkpoint import FitCheckpointer

            ckpt = FitCheckpointer.from_env(
                "logreg",
                {
                    "n_classes": n_classes,
                    "multinomial": multinomial,
                    "fit_intercept": fit_intercept,
                    "standardization": bool(params["standardization"]),
                    "l1": reg * l1_ratio,
                    "l2": reg * (1.0 - l1_ratio),
                    "max_iter": int(params["max_iter"]),
                    "tol": float(params["tol"]),
                    "n_rows": int(inputs.n_rows),
                    "d": int(inputs.n_features),
                },
            )
            out = streamed_logreg_fit(
                inputs.source,
                inputs.mesh,
                inputs.chunk_rows,
                inputs.dtype,
                n_classes=n_classes,
                multinomial=multinomial,
                fit_intercept=fit_intercept,
                standardization=bool(params["standardization"]),
                l1=reg * l1_ratio,
                l2=reg * (1.0 - l1_ratio),
                max_iter=int(params["max_iter"]),
                tol=float(params["tol"]),
                checkpointer=ckpt if ckpt.enabled else None,
            )
            return {
                "coef_": np.asarray(out["coef_"]),
                "intercept_": np.asarray(out["intercept_"]),
                "n_classes": n_classes,
                "multinomial": multinomial,
                "n_iter": int(out["n_iter"]),
                "objective": float(out["objective"]),
            }

        return _fit

    def _create_model(self, result: Dict[str, Any]) -> "LogisticRegressionModel":
        return LogisticRegressionModel(**result)


class LogisticRegressionModel(
    LogisticRegressionClass, _TpuModel, _LogisticRegressionParams
):
    def __init__(self, **attrs: Any) -> None:
        _TpuModel.__init__(self, **attrs)
        _LogisticRegressionParams.__init__(self)

    # -- attribute surface (Spark model API) -------------------------------
    @property
    def coef_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["coef_"])

    @property
    def intercept_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["intercept_"])

    @property
    def numClasses(self) -> int:
        return int(self._model_attributes["n_classes"])

    @property
    def numFeatures(self) -> int:
        return int(self.coef_.shape[-1])

    @property
    def _multinomial(self) -> bool:
        v = self._model_attributes["multinomial"]
        if isinstance(v, str):  # JSON round-trip through persistence
            return v == "True"
        return bool(np.asarray(v))

    @property
    def coefficients(self) -> np.ndarray:
        """Binary-model coefficient vector (Spark raises for multinomial)."""
        if self._multinomial:
            raise RuntimeError(
                "Multinomial model: use coefficientMatrix instead of coefficients"
            )
        return self.coef_.reshape(-1)

    @property
    def intercept(self) -> float:
        if self._multinomial:
            raise RuntimeError(
                "Multinomial model: use interceptVector instead of intercept"
            )
        return float(self.intercept_.reshape(-1)[0])

    @property
    def coefficientMatrix(self) -> np.ndarray:
        return np.atleast_2d(self.coef_)

    @property
    def interceptVector(self) -> np.ndarray:
        return np.atleast_1d(self.intercept_)

    @property
    def classes_(self) -> np.ndarray:
        return np.arange(self.numClasses, dtype=np.float64)

    @property
    def hasSummary(self) -> bool:
        return False

    @property
    def n_iter_(self) -> int:
        return int(self._model_attributes.get("n_iter", 0))

    # -- single-row helpers (Spark model API) ------------------------------
    def _scores(self, x: np.ndarray) -> np.ndarray:
        coef = np.atleast_2d(self.coef_).astype(np.float64)
        b = np.atleast_1d(self.intercept_).astype(np.float64)
        return x @ coef.T + b

    def predict(self, vector: Any) -> float:
        x = np.asarray(vector, dtype=np.float64).ravel()
        s = self._scores(x[None, :])[0]
        if self._multinomial:
            return float(np.argmax(s))
        return float(s[0] > 0)

    def predictRaw(self, vector: Any) -> np.ndarray:
        x = np.asarray(vector, dtype=np.float64).ravel()
        s = self._scores(x[None, :])[0]
        if self._multinomial:
            return s
        return np.asarray([-s[0], s[0]])

    def predictProbability(self, vector: Any) -> np.ndarray:
        raw = self.predictRaw(vector)
        if self._multinomial:
            e = np.exp(raw - raw.max())
            return e / e.sum()
        p1 = 1.0 / (1.0 + np.exp(-raw[1]))
        return np.asarray([1.0 - p1, p1])

    # -- transform ---------------------------------------------------------
    def _out_cols(self) -> List[str]:
        return [
            self.getOrDefault("predictionCol"),
            self.getOrDefault("probabilityCol"),
            self.getOrDefault("rawPredictionCol"),
        ]

    def _get_tpu_transform_func(
        self, dataset: Optional[DataFrame] = None
    ) -> Callable[[np.ndarray], Dict[str, np.ndarray]]:
        pred_col = self.getOrDefault("predictionCol")
        prob_col = self.getOrDefault("probabilityCol")
        raw_col = self.getOrDefault("rawPredictionCol")
        return self._memoized_transform_fn(
            ("logreg", pred_col, prob_col, raw_col),
            lambda: self._build_transform_fn(pred_col, prob_col, raw_col),
        )

    def _build_transform_fn(
        self, pred_col: str, prob_col: str, raw_col: str
    ) -> Callable[[np.ndarray], Dict[str, np.ndarray]]:
        coef_np = np.atleast_2d(self.coef_)
        b_np = np.atleast_1d(self.intercept_)
        multinomial = self._multinomial
        if not self._is_multi_model and not np.all(np.isfinite(b_np)):
            # degenerate single-label model: ±inf intercept would poison the
            # matmul; emit constant predictions directly
            const_pred = 1.0 if b_np.reshape(-1)[0] > 0 else 0.0

            def _const(Xb: np.ndarray) -> Dict[str, np.ndarray]:
                n = Xb.shape[0]
                pred = np.full((n,), const_pred, dtype=Xb.dtype)
                prob = np.zeros((n, 2), dtype=Xb.dtype)
                prob[:, int(const_pred)] = 1.0
                raw = np.zeros((n, 2), dtype=Xb.dtype)
                raw[:, int(const_pred)] = np.inf
                raw[:, 1 - int(const_pred)] = -np.inf
                return {pred_col: pred, prob_col: prob, raw_col: raw}

            return _const

        if self._is_multi_model:
            # CV-combined model: coef_ (m, K, d) -> per-model outputs
            # prediction (n, m), probability (n, m, K), raw (n, m, K)
            coef3 = self.coef_

            @jax.jit
            def _predict_multi(Xb: jax.Array):
                C = jnp.asarray(coef3, dtype=Xb.dtype)      # (m, K, d)
                B = jnp.asarray(np.atleast_2d(b_np), dtype=Xb.dtype)  # (m, K)
                scores = jnp.einsum("nd,mkd->nmk", Xb, C) + B[None, :, :]
                if multinomial:
                    raw = scores
                    prob = jax.nn.softmax(scores, axis=2)
                    pred = jnp.argmax(scores, axis=2).astype(Xb.dtype)
                else:
                    z = scores[..., 0]
                    raw = jnp.stack([-z, z], axis=2)
                    p1 = jax.nn.sigmoid(z)
                    prob = jnp.stack([1.0 - p1, p1], axis=2)
                    pred = (p1 > 0.5).astype(Xb.dtype)
                return pred, prob, raw

            def _fn_multi(Xb: np.ndarray) -> Dict[str, np.ndarray]:
                pred, prob, raw = _predict_multi(jnp.asarray(Xb))
                return {
                    pred_col: np.asarray(pred),
                    prob_col: np.asarray(prob),
                    raw_col: np.asarray(raw),
                }

            return _fn_multi

        def _fn(Xb: np.ndarray) -> Dict[str, np.ndarray]:
            pred, prob, raw = logreg_predict(
                jnp.asarray(Xb),
                jnp.asarray(coef_np, dtype=Xb.dtype),
                jnp.asarray(b_np, dtype=Xb.dtype),
                multinomial=multinomial,
            )
            return {
                pred_col: np.asarray(pred),
                prob_col: np.asarray(prob),
                raw_col: np.asarray(raw),
            }

        return _fn

    # -- multi-model support (CV single-pass) ------------------------------
    @classmethod
    def _combine(
        cls, models: List["LogisticRegressionModel"]
    ) -> "LogisticRegressionModel":
        """Stack models for single-pass multi-model evaluation (reference
        ``classification.py:1504-1519``)."""
        coefs = np.stack([np.atleast_2d(m.coef_) for m in models])  # (m, K, d)
        intercepts = np.stack([np.atleast_1d(m.intercept_) for m in models])
        combined = cls(
            coef_=coefs,
            intercept_=intercepts,
            n_classes=models[0].numClasses,
            multinomial=models[0]._multinomial,
            n_iter=0,
            objective=0.0,
        )
        models[0]._copyValues(combined)
        models[0]._copy_tpu_params(combined)
        return combined

    @property
    def _is_multi_model(self) -> bool:
        return self.coef_.ndim == 3

    def _transformEvaluate(self, dataset: DataFrame, evaluator: Any) -> List[float]:
        """ONE data pass -> per-model confusion/log-loss sufficient stats ->
        metric values (reference ``classification.py:153-272``)."""
        from ..evaluation import MulticlassClassificationEvaluator
        from ..metrics import MulticlassMetrics

        if not isinstance(evaluator, MulticlassClassificationEvaluator):
            raise NotImplementedError(
                f"Evaluator {type(evaluator).__name__} is not supported"
            )
        X = self._extract_features_for_transform(dataset)
        out = self._apply_batched(self._get_tpu_transform_func(dataset), X)
        preds = out[self.getOrDefault("predictionCol")]
        probs = out[self.getOrDefault("probabilityCol")]
        y = np.asarray(dataset.column(evaluator.getLabelCol()), dtype=np.float64)
        need_probs = evaluator.getMetricName() == "logLoss"
        if preds.ndim == 1:
            preds, probs = preds[:, None], probs[:, None, :]
        return [
            MulticlassMetrics.from_predictions(
                y,
                preds[:, j],
                probs[:, j, :] if need_probs else None,
                evaluator.getEps(),
            ).evaluate(evaluator)
            for j in range(preds.shape[1])
        ]
