"""Core estimator/model framework — the reference ``core.py`` re-designed TPU-first.

Reference architecture (``/root/reference/python/src/spark_rapids_ml/core.py``):
Spark barrier tasks each ingest Arrow batches into device arrays, bootstrap a
NCCL communicator, and call a per-algorithm closure returned by
``_get_cuml_fit_func``; rank 0 yields the model row back to the driver
(``core.py:615-780``). Transform is an embarrassingly-parallel pandas UDF
(``core.py:1463-1568``).

TPU-native redesign: there is no task/driver split — the host process owns a
``jax.sharding.Mesh``; ``_pre_process_data`` shards the design matrix over
the ``dp`` axis with ``NamedSharding`` and the per-algorithm fit function is
a **jitted global-math function** (psum/all_gather inserted by XLA's SPMD
partitioner, playing the role the NCCL allreduce played inside cuML).
The subclass contract is preserved one-to-one:

  reference hook                      this framework
  ---------------------------------   ---------------------------------
  ``_get_cuml_fit_func``              ``_get_tpu_fit_func``
  ``_get_cuml_transform_func``        ``_get_tpu_transform_func``
  ``_out_schema``                     (models return named arrays)
  ``_pre_process_data``               ``_pre_process_data``
  ``_require_nccl_ucx``               (absent — the mesh always exists)
  ``fitMultiple``/``_combine``        same names, same single-pass contract
  ``_transformEvaluate``              same name, same sufficient-stats design
"""

from __future__ import annotations

import json
import os
import shutil
from abc import abstractmethod
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from .data.dataframe import DataFrame, _is_sparse
from .params import Params, _TpuParams, HasLabelCol, HasPredictionCol, HasWeightCol
from .runtime import autotune, envspec, telemetry
from .parallel.mesh import (
    global_row_count,
    make_mesh,
    resolve_mesh_mp,
    row_sharding,
    shard_aligned,
    shard_rows,
)
from .utils.logging import get_logger


def _resolve_feature_matrix(obj: "_TpuParams", dataset: DataFrame):
    """Resolve the feature columns of ``dataset`` into one matrix.

    Single implementation shared by the fit and transform paths (reference
    column selection: ``core.py:449-546`` fit, ``core.py:1183-1303``
    transform). Returns ``(X_dense, X_sparse)`` — exactly one is non-None;
    ``X_sparse`` is a host scipy CSR and is only returned when the sparse
    opt-in resolves to True (``enable_sparse_data_optim`` semantics,
    reference ``params.py:42-63``).
    """
    input_col, input_cols = obj._get_input_columns()
    if input_cols is not None:
        mats = [np.asarray(dataset.column(c)).reshape(-1, 1) for c in input_cols]
        return np.concatenate(mats, axis=1), None
    col = dataset.column(input_col)
    if _is_sparse(col):
        use_sparse = True
        if obj.hasParam("enable_sparse_data_optim") and obj.isDefined(
            "enable_sparse_data_optim"
        ):
            if obj.getOrDefault("enable_sparse_data_optim") is False:
                use_sparse = False
        if use_sparse:
            return None, col
        return np.asarray(col.todense()), None
    X = np.asarray(col)
    if X.ndim != 2:
        raise ValueError(f"Features column {input_col!r} must be a 2-D vector column")
    return X, None

def _resolve_features_f32(obj: "_TpuParams", dataset: DataFrame) -> np.ndarray:
    """Resolve features to one dense contiguous float32 matrix — the shared
    path for float32-only algorithms (kNN, UMAP; reference ``knn.py:289-292``
    converts all inputs to float32)."""
    X, X_sparse = _resolve_feature_matrix(obj, dataset)
    if X is None:
        X = np.asarray(X_sparse.todense())
    return np.ascontiguousarray(np.asarray(X, dtype=np.float32))


def _x64_ctx(dtype: Any):
    """Scoped x64 enablement for the float64 path.

    The reference supports f64 inputs end-to-end (``float32_inputs=False``,
    reference ``params.py:301-305``). JAX truncates to 32-bit by default and
    toggling ``jax_enable_x64`` globally from a library import would change
    numerics of unrelated user code — so widen only around our own
    device_put/compute when the resolved input dtype is f64.
    """
    import contextlib

    from jax._src.config import enable_x64

    if jnp.dtype(dtype) == jnp.dtype("float64"):
        return enable_x64(True)
    return contextlib.nullcontext()


# one-time (per process) debug log of the gang-fit static-bucket partition
_GANG_PARTITION_LOGGED = False


def _default_gang_budget() -> float:
    """Default HBM budget for gang-fit lane residents: a quarter of the
    device memory limit (4 GB when the backend reports none, e.g. the CPU
    test mesh)."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = float(stats.get("bytes_limit", 0.0))
    except Exception:
        limit = 0.0
    if limit <= 0.0:
        limit = float(16 << 30)
    return limit / 4.0


def _gang_env_on() -> bool:
    """Cheap gate: is ``TPUML_GANG_FIT`` set to anything but off?

    Deliberately does NOT validate the value — :func:`resolve_gang_fit`
    does, so a typo'd value raises ``EnvSpecError`` on the gang path
    instead of silently running sequential."""
    return str(envspec.get("TPUML_GANG_FIT")).strip().lower() != "off"


def resolve_gang_fit(n_lanes: int, lane_bytes: float) -> int:
    """Lanes fitted per gang dispatch (1 = the sequential per-param loop).

    ``TPUML_GANG_FIT``: ``off`` (default) keeps the sequential path, an
    integer pins a lane width, ``auto`` targets the whole static bucket.
    The result is clamped to the widest gang whose per-lane residents
    (estimator's ``_gang_lane_bytes`` estimate — dominated by the (n, B, K)
    logits block and its backward twin) fit the HBM budget
    (``TPUML_GANG_FIT_BUDGET``, default a quarter of device memory) —
    mirroring the ``TPUML_RF_TREE_BATCH`` resolver.
    """
    raw = str(envspec.get("TPUML_GANG_FIT")).strip().lower()
    if raw == "off":
        return 1
    tune_key = None
    if raw == "auto":
        want = n_lanes
        if autotune.active():
            tune_key = autotune.shape_key(
                n=n_lanes, d=int(lane_bytes), dtype="lane_bytes"
            )
            tuned = autotune.consult("gang_fit", tune_key)
            if isinstance(tuned, int) and 1 <= tuned <= n_lanes:
                want = tuned
                tune_key = None  # provenance already filed by consult
    else:
        try:
            want = int(raw)
        except ValueError:
            raise envspec.EnvSpecError(
                f"TPUML_GANG_FIT={raw!r}: expected 'auto', 'off', or a "
                "positive integer"
            ) from None
        if want < 1:
            raise envspec.EnvSpecError(
                f"TPUML_GANG_FIT={want}: lane width must be >= 1"
            )
    budget = envspec.get("TPUML_GANG_FIT_BUDGET")
    budget = float(budget) if budget else _default_gang_budget()
    fit = max(1, int(budget // max(1.0, float(lane_bytes))))
    lanes = max(1, min(want, fit))
    if tune_key is not None:
        autotune.record_heuristic("gang_fit", tune_key, lanes)
    telemetry.record_hbm_estimate("gang_fit", float(lane_bytes) * lanes)
    return lanes


@dataclass
class FitInputs:
    """Everything a fit function needs: the sharded design matrix + metadata.

    Replaces the reference's per-task ``(dfs, params)`` closure inputs
    (``core.py:749-762``) and ``PartitionDescriptor`` (``utils.py:163-200``):
    ragged partitions become an even row-shard plus a validity mask.
    """

    X: jax.Array                     # (N_pad, d_padded) row-sharded over dp
    mask: jax.Array                  # (N_pad,) 1.0 valid / 0.0 padding
    mesh: Any
    n_rows: int                      # true (unpadded) row count
    n_features: int                  # true (logical) feature count
    y: Optional[jax.Array] = None    # (N_pad,) labels, padded with 0
    weight: Optional[jax.Array] = None
    X_sparse: Optional[Any] = None   # host scipy CSR when the sparse path is on
    dtype: Any = jnp.float32
    csize: int = 1                   # per-device row-chunk size (scan kernels)
    n_features_padded: int = 0       # X's column count incl. lane padding


# fit function: (inputs, params_dict) -> dict of named numpy arrays/scalars
FitFunc = Callable[[FitInputs, Dict[str, Any]], Dict[str, Any]]


@dataclass
class StreamInputs:
    """Chunked-fit inputs: a re-iterable source instead of resident arrays.

    The out-of-core analog of :class:`FitInputs` (reference Arrow-batch
    streaming + UVM, ``core.py:699-741``): device memory holds one chunk
    slab plus algorithm state, never the dataset.
    """

    source: Any                      # data.chunks.ChunkSource
    mesh: Any
    n_rows: int
    n_features: int
    dtype: Any = jnp.float32
    chunk_rows: int = 1 << 16


# streaming fit function: (stream_inputs, params_dict) -> named arrays
StreamFitFunc = Callable[[StreamInputs, Dict[str, Any]], Dict[str, Any]]


def _default_stream_threshold_bytes() -> int:
    """Dataset size above which fit streams instead of materializing.

    Overridable via ``TPUML_STREAM_THRESHOLD_BYTES``. Default: 60% of one
    device's reported memory (the design matrix must leave room for Gram
    temporaries), or 8 GiB when the backend doesn't report memory (CPU)."""
    env = envspec.get("TPUML_STREAM_THRESHOLD_BYTES")
    if env is not None:
        return int(env)
    try:
        stats = jax.local_devices()[0].memory_stats()
        limit = int(stats.get("bytes_limit", 0)) if stats else 0
        if limit > 0:
            return int(0.6 * limit * len(jax.local_devices()))
    except Exception:
        pass
    return 8 << 30


class _TpuEstimator(Params, _TpuParams):
    """Abstract estimator (reference ``_CumlEstimator``, ``core.py:834-1032``)."""

    def __init__(self) -> None:
        super().__init__()
        self._init_tpu_params()
        self.logger = get_logger(type(self))

    # ---- subclass hooks --------------------------------------------------
    @abstractmethod
    def _get_tpu_fit_func(self, dataset: DataFrame) -> FitFunc:
        ...

    @abstractmethod
    def _create_model(self, result: Dict[str, Any]) -> "_TpuModel":
        ...

    def _require_label(self) -> bool:
        return isinstance(self, HasLabelCol)

    def _supportsTransformEvaluate(self, evaluator: Any) -> bool:
        return False

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        return False

    def _get_tpu_streaming_fit_func(
        self, dataset: DataFrame
    ) -> Optional[StreamFitFunc]:
        """Chunked out-of-core fit, or None when the algorithm requires the
        resident-matrix path. Engaged by :meth:`_should_stream`."""
        return None

    # ---- gang-fit hooks --------------------------------------------------
    def _gang_fit_groups(
        self, param_sets: List[Dict[str, Any]]
    ) -> Optional[List[Tuple[Any, List[int]]]]:
        """Static-bucket partition of ``param_sets`` for the gang path: a
        list of ``(bucket_key, [lane indices])`` where every lane in a
        bucket shares the batched kernel's *static* parameters (continuous
        params ride traced ``(B,)`` lane arrays and never split a bucket).
        ``None`` (default): estimator has no gang path."""
        return None

    def _get_tpu_gang_fit_func(
        self, dataset: DataFrame
    ) -> Optional[Callable[..., List[Dict[str, Any]]]]:
        """Gang companion of :meth:`_get_tpu_fit_func`: returns
        ``fn(inputs, group_param_sets, **fold_kwargs) -> [result, ...]``
        fitting one whole static bucket in a single device dispatch, or
        ``None`` when this dataset can't gang (e.g. degenerate labels)."""
        return None

    def _gang_fit_supports_folds(self) -> bool:
        """Whether the gang fit func accepts ``fold_id``/``lane_fold``/
        ``n_folds`` for fold-masked CV lanes."""
        return False

    def _gang_lane_bytes(self, inputs: "FitInputs") -> float:
        """Estimated HBM bytes each additional gang lane keeps resident
        (drives the ``TPUML_GANG_FIT_BUDGET`` clamp). Default assumes a
        few f32 row-vector temporaries per lane."""
        return 16.0 * float(inputs.X.shape[0])

    def _gang_dispatch(
        self,
        inputs: "FitInputs",
        param_sets: List[Dict[str, Any]],
        *,
        gang_fit: Callable[..., List[Dict[str, Any]]],
        cls_name: str,
        fold_id: Optional[jax.Array] = None,
        lane_folds: Optional[List[int]] = None,
        n_folds: int = 0,
        allow_singletons: bool = False,
    ) -> Tuple[Dict[int, Dict[str, Any]], Dict[int, Dict[str, Any]], Dict[int, Dict[str, int]]]:
        """Fit as many lanes of ``param_sets`` as the resolver allows in
        batched device dispatches. Returns ``(results, reports, res_deltas)``
        keyed by lane index; lanes NOT in the maps fall through to the
        caller's sequential loop (singleton chunks stay sequential so solo
        numerics are untouched, unless ``allow_singletons`` — the fold-masked
        CV path — where even a stray lane needs the batched kernel)."""
        global _GANG_PARTITION_LOGGED
        groups = self._gang_fit_groups(param_sets)
        if not groups:
            return {}, {}, {}
        from .runtime import counters as _res_counters
        from .utils.profiling import annotate, timed

        lane_bytes = float(self._gang_lane_bytes(inputs))
        min_chunk = 1 if allow_singletons else 2
        plan: List[Tuple[Any, List[int]]] = []
        for key, idxs in groups:
            width = resolve_gang_fit(len(idxs), lane_bytes)
            if width < min_chunk:
                continue
            for c0 in range(0, len(idxs), width):
                chunk = idxs[c0 : c0 + width]
                if len(chunk) >= min_chunk:
                    plan.append((key, chunk))
        if not plan:
            return {}, {}, {}
        if not _GANG_PARTITION_LOGGED:
            self.logger.debug(
                "gang-fit static-bucket partition: %s",
                [(str(k), len(c)) for k, c in plan],
            )
            _GANG_PARTITION_LOGGED = True
        results: Dict[int, Dict[str, Any]] = {}
        reports: Dict[int, Dict[str, Any]] = {}
        deltas: Dict[int, Dict[str, int]] = {}
        for key, chunk in plan:
            res_base = _res_counters.snapshot()
            group_ps = [param_sets[i] for i in chunk]
            kw: Dict[str, Any] = {}
            if fold_id is not None:
                assert lane_folds is not None
                kw = dict(
                    fold_id=fold_id,
                    lane_fold=np.asarray([lane_folds[i] for i in chunk], np.int32),
                    n_folds=n_folds,
                )
            with annotate(f"{cls_name}.gang_fit"), timed(
                self.logger, "gang_fit"
            ), telemetry.span(
                f"{cls_name}.gang_fit",
                lanes=len(chunk),
                bucket=str(key),
            ) as g_span:
                outs = gang_fit(inputs, group_ps, **kw)
                g_span.fence(outs)
            res_delta = _res_counters.delta_since(res_base)
            _res_counters.bump("gang_dispatches")
            _res_counters.bump("gang_lanes_total", len(chunk))
            for lane_pos, i in enumerate(chunk):
                results[i] = outs[lane_pos]
                deltas[i] = res_delta
                reports[i] = {
                    "gang_lanes": len(chunk),
                    "gang_groups": len(plan),
                    "gang_bucket": str(key),
                }
                if lane_folds is not None:
                    reports[i]["gang_fold"] = int(lane_folds[i])
        return results, reports, deltas

    def _gang_cv_fit_multiple(
        self,
        dataset: DataFrame,
        paramMaps: Sequence[Dict[Any, Any]],
        n_folds: int,
        seed: int,
    ) -> Optional[List[List["_TpuModel"]]]:
        """Fold-masked gang CV: fit the whole ``n_folds × len(paramMaps)``
        grid as gang lanes over ONE resident X, each lane's objective
        masking ``fold_id == lane_fold`` rows on the fly. Returns
        ``models[fold][map]`` or ``None`` (caller falls back to the
        per-fold sequential path). All-or-nothing: a grid that can't gang
        completely is declined rather than half-ganged."""
        if not _gang_env_on():
            return None
        if self._gang_fit_supports_folds() is False:
            return None
        stream_func = self._get_tpu_streaming_fit_func(dataset)
        if stream_func is not None and self._should_stream(dataset):
            # fold masking needs the resident design matrix
            return None
        gang_fit = self._get_tpu_gang_fit_func(dataset)
        if gang_fit is None:
            return None
        with _x64_ctx(np.float64 if not self._float32_inputs else np.float32):
            return self._gang_cv_fit_x64scoped(
                dataset, paramMaps, n_folds, seed, gang_fit
            )

    def _gang_cv_fit_x64scoped(
        self,
        dataset: DataFrame,
        paramMaps: Sequence[Dict[Any, Any]],
        n_folds: int,
        seed: int,
        gang_fit: Callable[..., List[Dict[str, Any]]],
    ) -> Optional[List[List["_TpuModel"]]]:
        from .data.dataframe import kfold_ids
        from .utils.profiling import annotate, timed

        self._apply_verbosity()
        cls_name = type(self).__name__
        with annotate(f"{cls_name}.preprocess"), timed(
            self.logger, "preprocess"
        ), telemetry.span("preprocess", gang_cv=True):
            inputs = self._pre_process_data(dataset)
        # the SAME seeded draw kfold() makes, so masked lanes see exactly
        # the rows the sequential per-fold path trains on
        fold_host = kfold_ids(dataset.count(), n_folds, seed)
        fold_dev = shard_aligned(
            fold_host.astype(np.int32), inputs.mesh, inputs.X.shape[0]
        )
        estimators: List[_TpuEstimator] = []
        map_param_sets: List[Dict[str, Any]] = []
        for pm in paramMaps:
            est = self.copy()
            self._copy_tpu_params(est)
            kw = {p.name if hasattr(p, "name") else p: v for p, v in pm.items()}
            est._set_params(**kw)
            estimators.append(est)
            map_param_sets.append(dict(est._tpu_params))
        lanes = [(f, j) for f in range(n_folds) for j in range(len(paramMaps))]
        lane_ps = [map_param_sets[j] for _, j in lanes]
        lane_folds = [f for f, _ in lanes]
        results, reports, deltas = self._gang_dispatch(
            inputs,
            lane_ps,
            gang_fit=gang_fit,
            cls_name=cls_name,
            fold_id=fold_dev,
            lane_folds=lane_folds,
            n_folds=n_folds,
            allow_singletons=True,
        )
        if len(results) < len(lanes):
            return None
        out: List[List[_TpuModel]] = []
        for f in range(n_folds):
            row: List[_TpuModel] = []
            for j in range(len(paramMaps)):
                i = lanes.index((f, j))
                est = estimators[j]
                model = est._create_model(results[i])
                est._copyValues(model)
                est._copy_tpu_params(model)
                model._resilience_report = deltas.get(i, {})
                model._fit_report = reports[i]
                row.append(model)
            out.append(row)
        return out

    def _resolved_weight_col(self) -> Optional[str]:
        """The explicitly-set weight column, or None — the ONE definition
        of weight-col eligibility shared by the stream gate and both data
        planes."""
        if (
            isinstance(self, HasWeightCol)
            and self.hasParam("weightCol")
            and self.isSet("weightCol")
            and self.getOrDefault("weightCol") is not None
        ):
            return self.getOrDefault("weightCol")
        return None

    # ---- streaming decision / data plane --------------------------------
    def _should_stream(self, dataset: DataFrame) -> bool:
        if self._streaming is not None:
            return bool(self._streaming)
        from .data.dataframe import ParquetScanFrame

        input_col, input_cols = self._get_input_columns()
        if isinstance(dataset, ParquetScanFrame) and not dataset.is_materialized():
            # multi-column features are resident-only, and streaming can
            # only read DISK-backed columns: a chained stage whose
            # features/label col is a prior transform's in-memory output
            # (AugmentedScanFrame) takes the materializing path
            if input_cols is not None:
                return False
            needed = [input_col]
            if self._require_label():
                needed.append(self.getOrDefault("labelCol"))
            wcol = self._resolved_weight_col()
            if wcol is not None:
                needed.append(wcol)
            return all(dataset.has_disk_column(c) for c in needed)
        if input_cols is not None:
            n_features = len(input_cols)
        else:
            col = dataset.column(input_col)
            if (
                _is_sparse(col)
                and self.hasParam("enable_sparse_data_optim")
                and self.isDefined("enable_sparse_data_optim")
                and self.getOrDefault("enable_sparse_data_optim") is True
            ):
                # explicit sparse opt-in (reference ``params.py:42-63``):
                # chunked-CSR streaming is the sparse compute path — the
                # matrix must never densify in full
                return True
            n_features = int(col.shape[1]) if col.ndim == 2 or _is_sparse(col) else 1
        itemsize = 4 if self._float32_inputs else 8
        # GLOBAL row count: the stream-vs-resident decision is a
        # compile-time constant all ranks must agree on (ranks deciding
        # differently would issue mismatched collectives and deadlock)
        est_bytes = global_row_count(dataset.count()) * n_features * itemsize
        return est_bytes > _default_stream_threshold_bytes()

    def _pre_process_stream(self, dataset: DataFrame) -> StreamInputs:
        import jax as _jax

        from .data.chunks import (
            ArrayChunkSource,
            CSRChunkSource,
            auto_chunk_rows,
        )
        from .data.dataframe import ParquetScanFrame
        from .parallel.mesh import local_mesh

        if _jax.process_count() > 1:
            # streaming is partition-local: each process streams its chunks
            # through its OWN chips; cross-process combination happens at
            # the sufficient-statistics level (ops/streaming.py allreduces
            # partials — the reference's per-worker Arrow stream + NCCL
            # allreduce architecture)
            mesh = local_mesh()
        else:
            mesh = make_mesh(self.num_workers)
        label_col = (
            self.getOrDefault("labelCol") if self._require_label() else None
        )
        weight_col = self._resolved_weight_col()

        input_col, input_cols = self._get_input_columns()
        scan_cols_on_disk = all(
            dataset.has_disk_column(c)
            for c in [input_col, label_col, weight_col]
            if c is not None
        ) if isinstance(dataset, ParquetScanFrame) else False
        if (
            isinstance(dataset, ParquetScanFrame)
            and not dataset.is_materialized()
            and scan_cols_on_disk
        ):
            # NOT scan_cols_on_disk: a column lives only in memory (e.g. a
            # prior streaming transform's output, possibly SHADOWING a
            # same-named disk column) — the in-memory branch below reads
            # the authoritative values via dataset.column()
            if input_cols is not None:
                raise ValueError(
                    "streaming fit over a parquet scan requires a single "
                    "vector features column (featuresCols is resident-only)"
                )
            source = dataset.chunk_source(
                features_col=input_col, label_col=label_col, weight_col=weight_col
            )
            dtype = np.float32 if self._float32_inputs else np.float64
        else:
            X, X_sparse = _resolve_feature_matrix(self, dataset)
            y = (
                np.asarray(dataset.column(label_col))
                if label_col is not None
                else None
            )
            w = (
                np.asarray(dataset.column(weight_col))
                if weight_col is not None
                else None
            )
            if X_sparse is not None:
                dtype = np.float32 if self._float32_inputs else np.float64
                source = CSRChunkSource(X_sparse, y, w)
            else:
                dtype = self._target_dtype(X)
                source = ArrayChunkSource(X, y, w)

        chunk_rows = self._stream_chunk_rows or auto_chunk_rows(
            source.n_features, np.dtype(dtype).itemsize, mesh.shape["dp"]
        )
        n_dp = mesh.shape["dp"]
        chunk_rows = max(n_dp, (chunk_rows // n_dp) * n_dp)
        return StreamInputs(
            source=source,
            mesh=mesh,
            n_rows=global_row_count(int(source.n_rows)),
            n_features=int(source.n_features),
            dtype=jnp.dtype(dtype),
            chunk_rows=int(chunk_rows),
        )

    # ---- data plane ------------------------------------------------------
    def _target_dtype(self, X: Optional[np.ndarray]) -> Any:
        if self._float32_inputs:
            return np.float32
        if X is not None and X.dtype == np.float64:
            return np.float64
        return np.float32

    def _chunk_rows(self, n_rows: int, n_dp: int) -> int:
        """Per-device scan chunk size; subclasses with chunked-scan kernels
        override (rows are padded so each shard is a multiple of this)."""
        return 1

    @staticmethod
    def _equal_chunk_rows(n_rows: int, n_dp: int, cap: int) -> int:
        """Smallest chunk <= cap that divides each device's shard into equal
        pieces: bounds padding to < n_chunks rows/device (vs up to cap-1)."""
        per_dev = max(1, -(-n_rows // n_dp))
        n_chunks = -(-per_dev // cap)
        return -(-per_dev // n_chunks)

    @staticmethod
    def rows_chunkable(n_padded_rows: int, mesh: Any, csize: int) -> bool:
        """True when a row-sharded array of ``n_padded_rows`` can take a
        chunked-scan kernel path: a real chunk size and per-device rows
        divisible by it (the ``shard_rows`` padding invariant). Single
        source of truth for the gate used by PCA/LinearRegression fits."""
        from .parallel.mesh import DP_AXIS

        return (
            csize is not None
            and csize > 1
            and n_padded_rows % (csize * mesh.shape[DP_AXIS]) == 0
        )

    def _feature_pad_multiple(self) -> int:
        """Column multiple to zero-pad the design matrix to before sharding
        (0 = none). Estimators whose fit kernel reads X inside a
        ``while_loop`` (KMeans) override: at lane-unaligned d XLA inserts a
        defensive full copy of X around the loop, and on TPU the minor dim
        is physically tiled to 128 anyway, so explicit zero columns cost no
        extra HBM while removing the 2x copy."""
        return 0

    def _x_placement_dtype(self) -> Optional[Any]:
        """Device dtype the design matrix is PLACED in (None = the resolved
        input dtype). Estimators whose fit kernel reads X in a narrower
        dtype (LogisticRegression's bf16 objective) override: placing X
        narrow from the host halves H2D bytes and — critically — avoids an
        in-program ``astype``, which would hold the wide argument and the
        narrow copy live at once (OOM at near-HBM scales). Labels, weights,
        masks and solver state keep the resolved input dtype."""
        return None

    def _model_axis_bytes(self, n_features_padded: int, dtype) -> float:
        """Bytes of the largest structure the estimator can shard along the
        model (``mp``) axis — what ``TPUML_MESH_MP=auto`` budgets against.
        Default: the d×d Gram/covariance accumulator (PCA, the linear
        solvers). Estimators whose model axis is not feature-squared
        (KMeans centroids, IVF lists) override."""
        return float(n_features_padded) ** 2 * np.dtype(dtype).itemsize

    def _pre_process_data(self, dataset: DataFrame) -> FitInputs:
        X, X_sparse = _resolve_feature_matrix(self, dataset)
        if X_sparse is not None:
            # Sparse path: the device arrays are densified (TPUs have no
            # sparse MXU path); the host CSR is kept on FitInputs so solvers
            # with a dedicated sparse formulation (LogisticRegression) can
            # stream it instead. Reference CSR ingestion: ``core.py:196-241``.
            n_rows, n_features = X_sparse.shape
            dtype = self._target_dtype(None)
        else:
            dtype = self._target_dtype(X)
            X = np.ascontiguousarray(X, dtype=dtype)
            n_rows, n_features = X.shape
        pad_mult = self._feature_pad_multiple()
        d_padded = int(n_features)
        if pad_mult > 0 and n_features % pad_mult:
            d_padded = -(-int(n_features) // pad_mult) * pad_mult
        # model-axis degree is resolved AFTER the feature width is known so
        # TPUML_MESH_MP=auto can budget against the estimator's dominant
        # model-axis structure (the d×d Gram by default)
        mp = resolve_mesh_mp(self._model_axis_bytes(d_padded, dtype))
        mesh = make_mesh(self.num_workers, mp=mp)
        # chunk size must be agreed across the process world (it shapes the
        # compiled program and its collectives): derive it from the GLOBAL
        # row count, never the local partition size
        n_global = global_row_count(int(n_rows))
        csize = self._chunk_rows(n_global, mesh.shape["dp"])
        if X_sparse is not None:
            X = np.asarray(X_sparse.todense(), dtype=dtype)
        if d_padded != n_features:
            X = np.pad(X, ((0, 0), (0, d_padded - int(n_features))))
        place = self._x_placement_dtype()
        if place is not None and np.dtype(dtype) == np.dtype(np.float32):
            X = X.astype(place)
        Xd, maskd = shard_rows(X, mesh, csize)

        y = w = None
        if self._require_label():
            label_col = self.getOrDefault("labelCol")
            y_host = np.asarray(dataset.column(label_col), dtype=dtype)
            y = shard_aligned(y_host, mesh, Xd.shape[0])
        wcol = self._resolved_weight_col()
        if wcol is not None:
            if wcol not in dataset:
                raise ValueError(
                    f"weightCol {wcol!r} not found in dataset columns {dataset.columns}"
                )
            w_host = np.asarray(dataset.column(wcol), dtype=dtype)
            w = shard_aligned(w_host, mesh, Xd.shape[0])

        return FitInputs(
            X=Xd,
            mask=maskd,
            mesh=mesh,
            n_rows=n_global,
            n_features=int(n_features),
            y=y,
            weight=w,
            X_sparse=X_sparse,
            dtype=jnp.dtype(dtype),
            csize=csize,
            n_features_padded=d_padded,
        )

    # ---- fit -------------------------------------------------------------
    def fit(self, dataset: DataFrame, params: Optional[Dict[Any, Any]] = None) -> "_TpuModel":
        if params:
            est = self.copy()
            self._copy_tpu_params(est)
            kw = {p.name if hasattr(p, "name") else p: v for p, v in params.items()}
            est._set_params(**kw)
            return est.fit(dataset)
        models = self._fit_internal(dataset, None)
        return models[0]

    def fitMultiple(
        self, dataset: DataFrame, paramMaps: Sequence[Dict[Any, Any]]
    ) -> Iterator[Tuple[int, "_TpuModel"]]:
        """Fit all param maps in ONE data pass (reference ``core.py:863-892``):
        the design matrix is sharded onto the mesh once and every param set
        reuses the resident device arrays."""
        if self._enable_fit_multiple_in_single_pass():
            models = self._fit_internal(dataset, list(paramMaps))
        else:
            models = [self.fit(dataset, pm) for pm in paramMaps]
        return _FitMultipleIterator(models)

    def _fit_internal(
        self, dataset: DataFrame, paramMaps: Optional[List[Dict[Any, Any]]]
    ) -> List["_TpuModel"]:
        with _x64_ctx(np.float64 if not self._float32_inputs else np.float32):
            return self._fit_internal_x64scoped(dataset, paramMaps)

    def _fit_internal_x64scoped(
        self, dataset: DataFrame, paramMaps: Optional[List[Dict[Any, Any]]]
    ) -> List["_TpuModel"]:
        # root telemetry span: every preprocess/dispatch/streaming span
        # of this fit nests under it, so the exported trace accounts the
        # fit's full wall time
        with telemetry.span(f"{type(self).__name__}.fit"):
            return self._fit_lanes_x64scoped(dataset, paramMaps)

    def _fit_coscheduled(
        self, dataset: DataFrame, estimators: List["_TpuEstimator"]
    ) -> List["_TpuModel"]:
        """Gang entry point for the fit scheduler (`runtime/scheduler.py`):
        fit several ready estimator instances of this class over one shared
        dataset in a single pass — one preprocess sharding the design
        matrix once, gang-batched lanes when the kernel supports it (same
        `TPUML_GANG_FIT` gating as `fitMultiple`), sequential lanes
        otherwise. Returns models order-aligned with ``estimators``."""
        with _x64_ctx(np.float64 if not self._float32_inputs else np.float32):
            with telemetry.span(
                f"{type(self).__name__}.fit", coscheduled=len(estimators)
            ):
                return self._fit_lanes_x64scoped(
                    dataset, None, coscheduled=estimators
                )

    def _fit_lanes_x64scoped(
        self,
        dataset: DataFrame,
        paramMaps: Optional[List[Dict[Any, Any]]],
        coscheduled: Optional[List["_TpuEstimator"]] = None,
    ) -> List["_TpuModel"]:
        # phase annotations land as named ranges on the profiler timeline
        # (the reference's NVTX ranges, ``RapidsRowMatrix.scala:62,70``)
        from .utils.profiling import annotate, timed

        self._apply_verbosity()
        cls_name = type(self).__name__
        stream_func = self._get_tpu_streaming_fit_func(dataset)
        streaming = stream_func is not None and self._should_stream(dataset)
        if streaming:
            self.logger.info(
                "Streaming fit engaged (out-of-core chunked ingestion)."
            )
            with annotate(f"{cls_name}.preprocess"), timed(
                self.logger, "preprocess"
            ), telemetry.span("preprocess", streaming=True):
                inputs: Any = self._pre_process_stream(dataset)
            fit_func: Any = stream_func
        else:
            with annotate(f"{cls_name}.preprocess"), timed(
                self.logger, "preprocess"
            ), telemetry.span("preprocess", streaming=False):
                inputs = self._pre_process_data(dataset)
            fit_func = self._get_tpu_fit_func(dataset)
        models: List[_TpuModel] = []
        param_sets: List[Dict[str, Any]]
        if coscheduled is not None:
            # scheduler gang: the lanes are ready estimator instances
            # (each tenant's own object), not paramMaps over self
            estimators = list(coscheduled)
            param_sets = [dict(est._tpu_params) for est in estimators]
        elif paramMaps is None:
            param_sets = [dict(self._tpu_params)]
            estimators = [self]
        else:
            estimators = []
            param_sets = []
            for pm in paramMaps:
                est = self.copy()
                self._copy_tpu_params(est)
                kw = {p.name if hasattr(p, "name") else p: v for p, v in pm.items()}
                est._set_params(**kw)
                estimators.append(est)
                param_sets.append(dict(est._tpu_params))
        from .runtime import counters as _res_counters

        # gang path: batch param lanes sharing static kernel params into one
        # device dispatch over the resident X. Env-gated (TPUML_GANG_FIT,
        # default off) so the default path below is bit-identical to HEAD;
        # any lane the gang declines (off, singleton bucket, streaming,
        # estimator without a gang kernel) falls through to the loop.
        gang_results: Dict[int, Dict[str, Any]] = {}
        gang_reports: Dict[int, Dict[str, Any]] = {}
        gang_deltas: Dict[int, Dict[str, int]] = {}
        gang_tuned: List[Dict[str, Any]] = []
        if not streaming and len(param_sets) > 1 and _gang_env_on():
            gang_fit = self._get_tpu_gang_fit_func(dataset)
            if gang_fit is not None:
                with autotune.collect() as gang_tuned:
                    gang_results, gang_reports, gang_deltas = (
                        self._gang_dispatch(
                            inputs,
                            param_sets,
                            gang_fit=gang_fit,
                            cls_name=cls_name,
                        )
                    )

        for lane, (est, ps) in enumerate(zip(estimators, param_sets)):
            if lane in gang_results:
                model = est._create_model(gang_results[lane])
                est._copyValues(model)
                est._copy_tpu_params(model)
                model._resilience_report = gang_deltas.get(lane, {})
                fit_report = gang_reports[lane]
                if gang_tuned:
                    fit_report = dict(fit_report or {})
                    fit_report["autotuned"] = list(gang_tuned)
                model._fit_report = fit_report
                models.append(model)
                continue
            res_base = _res_counters.snapshot()
            with autotune.collect() as tuned, annotate(
                f"{cls_name}.fit"
            ), timed(
                self.logger, "fit"
            ), telemetry.span(
                "fit.dispatch", lane=lane, streaming=streaming
            ) as d_span:
                result = fit_func(inputs, ps)
                d_span.fence(result)
            # fit provenance (model-axis degree, per-shard bytes, ...) rides
            # out of the kernel beside the model arrays; strip it before the
            # estimator unpacks result into model constructor kwargs. Absent
            # on the defaults path — reports attach only when a knob engaged.
            fit_report = result.pop("_fit_report", None) if isinstance(result, dict) else None
            if tuned:
                # knob decisions the tuner made during this dispatch —
                # value + provenance (cache_hit|probed|heuristic). Absent
                # (never an empty list) while TPUML_AUTOTUNE is off.
                fit_report = dict(fit_report or {})
                fit_report["autotuned"] = list(tuned)
            model = est._create_model(result)
            est._copyValues(model)
            est._copy_tpu_params(model)
            # resilience provenance: what the runtime had to do to land
            # this fit (retries/halvings/resume). Empty dict — and no log
            # line — on the clean path.
            res_delta = _res_counters.delta_since(res_base)
            model._resilience_report = res_delta
            if fit_report:
                model._fit_report = fit_report
            if res_delta:
                self.logger.info("resilience events during fit: %s", res_delta)
            if streaming:
                # ingest provenance: the wire encoding + pipeline depths the
                # chunk stream actually used (resolved knobs, not requested)
                from .ops.streaming import last_ingest_report

                model._ingest_report = last_ingest_report()
            models.append(model)
        return models

    # ---- persistence -----------------------------------------------------
    def write(self) -> "_Writer":
        return _Writer(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def read(cls) -> "_Reader":
        return _Reader(cls)

    @classmethod
    def load(cls, path: str) -> "_TpuEstimator":
        return cls.read().load(path)

    def _get_model_attributes(self) -> Optional[Dict[str, Any]]:
        return None


class _FitMultipleIterator:
    """Thread-safe (index, model) iterator (reference ``core.py:789-831``)."""

    def __init__(self, models: List["_TpuModel"]):
        import threading

        self._models = models
        self._index = 0
        self._lock = threading.Lock()

    def __iter__(self) -> "_FitMultipleIterator":
        return self

    def __next__(self) -> Tuple[int, "_TpuModel"]:
        with self._lock:
            i = self._index
            if i >= len(self._models):
                raise StopIteration
            self._index += 1
        return i, self._models[i]


class _TpuEstimatorSupervised(_TpuEstimator, HasLabelCol):
    """Adds label handling (reference ``_CumlEstimatorSupervised``,
    ``core.py:1039-1092``)."""

    def _require_label(self) -> bool:
        return True


class _TpuModel(Params, _TpuParams):
    """Abstract fitted model (reference ``_CumlModel``, ``core.py:1101-1364``)."""

    # subclasses list their array attributes for persistence
    _model_attribute_names: List[str] = []

    # resilience events observed during this model's fit (runtime/counters
    # delta; {} on a clean path). Class-level default so models that never
    # went through a fit loop (e.g. load()ed from disk) still expose it.
    _resilience_report: Dict[str, int] = {}

    # gang-fit provenance ({"gang_lanes": B, "gang_groups": G,
    # "gang_bucket": key} when this model came out of a batched dispatch;
    # {} on the sequential path).
    _fit_report: Dict[str, Any] = {}

    # ingest provenance of a STREAMED fit (resolved wire dtype + pipeline
    # depths from ops.streaming.last_ingest_report); {} for resident fits
    # and load()ed models.
    _ingest_report: Dict[str, Any] = {}

    def __init__(self, **model_attributes: Any) -> None:
        super().__init__()
        self._init_tpu_params()
        self._model_attributes = model_attributes
        self.logger = get_logger(type(self))

    def _get_model_attributes(self) -> Dict[str, Any]:
        return self._model_attributes

    # ---- transform -------------------------------------------------------
    @abstractmethod
    def _get_tpu_transform_func(
        self, dataset: Optional[DataFrame] = None
    ) -> Callable[[np.ndarray], Dict[str, np.ndarray]]:
        """Return fn: host feature batch (n, d) -> dict of output columns.

        The returned fn should wrap a jitted kernel; core handles batching
        and column wiring (reference ``_get_cuml_transform_func``,
        ``core.py:1137-1167``)."""
        ...

    def _out_cols(self) -> List[str]:
        cols = []
        if isinstance(self, HasPredictionCol):
            cols.append(self.getOrDefault("predictionCol"))
        return cols

    def _memoized_transform_fn(
        self,
        key: Tuple[Any, ...],
        build: Callable[[], Callable[[np.ndarray], Dict[str, np.ndarray]]],
    ) -> Callable[[np.ndarray], Dict[str, np.ndarray]]:
        """Cache a transform closure on the model, keyed by everything it
        hoisted (output columns, engine knobs, params). A fresh closure
        per ``transform()`` call means a fresh ``jax.jit`` object — its
        trace cache starts empty, so every call retraces and re-stages
        the hoisted operands. Repeated transforms (the serving hot path)
        must hit the same jitted program, so the closure lives here."""
        cache = getattr(self, "_transform_fn_cache", None)
        if cache is None:
            cache = self._transform_fn_cache = {}
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = build()
        return fn

    def transform(self, dataset: DataFrame) -> DataFrame:
        """Append prediction/output columns (reference ``core.py:1463-1568``).

        Embarrassingly parallel: rows are processed in device-sized batches;
        no collectives (matching the reference, which builds no communicator
        for transform)."""
        from .data.dataframe import AugmentedScanFrame, ParquetScanFrame
        from .utils.profiling import annotate, timed

        self._apply_verbosity()
        if isinstance(dataset, ParquetScanFrame) and not dataset.is_materialized():
            # out-of-core transform (the reference transforms per Arrow
            # batch, ``core.py:1463-1568``): stream chunks through the
            # jitted transform; host memory holds the OUTPUT columns only
            # (O(n) scalars/embeddings), never the feature matrix. Only
            # when the input column lives ON DISK: a chained transform
            # whose featuresCol is a prior stage's in-memory output column
            # (AugmentedScanFrame) takes the materializing path below.
            input_col, input_cols = self._get_input_columns()
            if input_cols is None and dataset.has_disk_column(input_col):
                np_dtype = np.dtype(
                    np.float32 if self._float32_inputs else np.float64
                )
                with _x64_ctx(np_dtype):
                    fn = self._get_tpu_transform_func(dataset)
                    with annotate(f"{type(self).__name__}.transform"), timed(
                        self.logger, "transform(streamed)"
                    ), telemetry.span(
                        f"{type(self).__name__}.transform", streamed=True
                    ):
                        out_columns = self._apply_streamed(fn, dataset, input_col)
                    self._log_transform_stages()
                return AugmentedScanFrame(dataset, out_columns)
        X = self._extract_features_for_transform(dataset)
        with _x64_ctx(X.dtype):
            fn = self._get_tpu_transform_func(dataset)
            with annotate(f"{type(self).__name__}.transform"), timed(
                self.logger, "transform"
            ), telemetry.span(
                f"{type(self).__name__}.transform", streamed=False
            ):
                out_columns = self._apply_batched(fn, X)
            self._log_transform_stages()
        out = dataset
        for name, col in out_columns.items():
            out = out.withColumn(name, col)
        return out

    def _log_transform_stages(self) -> None:
        """Emit the per-stage wall-clock breakdown a transform engine
        accumulated (models attach a ``profiling.StageTimer`` as
        ``_transform_stage_timer``; no-op otherwise)."""
        st = getattr(self, "_transform_stage_timer", None)
        if st is not None:
            st.log_summary(self.logger)

    def _apply_streamed(
        self,
        fn: Callable[[np.ndarray], Dict[str, np.ndarray]],
        scan: Any,
        input_col: str,
    ) -> Dict[str, np.ndarray]:
        source = scan.chunk_source(features_col=input_col)
        bs = self._transform_batch_rows()
        dtype = np.float32 if self._float32_inputs else np.float64
        chunks: Dict[str, List[np.ndarray]] = {}
        for chunk in source.iter_chunks(bs, dtype=dtype):
            Xb = np.ascontiguousarray(chunk.X[: chunk.n_valid], dtype=dtype)
            for k, v in fn(Xb).items():
                chunks.setdefault(k, []).append(np.asarray(v)[: chunk.n_valid])
        return {k: np.concatenate(v, axis=0) for k, v in chunks.items()}

    def _extract_features_for_transform(self, dataset: DataFrame) -> np.ndarray:
        X, X_sparse = _resolve_feature_matrix(self, dataset)
        if X is None:
            X = np.asarray(X_sparse.todense())
        dtype = np.float32 if self._float32_inputs else X.dtype
        return np.ascontiguousarray(X, dtype=dtype)

    def _transform_batch_rows(self) -> int:
        return 1 << 17  # 131072 rows/batch keeps HBM use bounded

    # Models whose transform kernels accept committed device arrays set
    # this to overlap host->device staging of batch i+1 with batch i's
    # compute (the async dispatch returns before device work finishes, so
    # the explicit device_put below it runs during the previous batch).
    _transform_device_staging = False

    def _apply_batched(
        self,
        fn: Callable[[np.ndarray], Dict[str, np.ndarray]],
        X: np.ndarray,
    ) -> Dict[str, np.ndarray]:
        staging = self._transform_device_staging
        n = X.shape[0]
        bs = self._transform_batch_rows()
        if n <= bs:
            Xb = jax.device_put(X) if staging else X
            return {k: np.asarray(v)[:n] for k, v in fn(Xb).items()}
        chunks: Dict[str, List[np.ndarray]] = {}
        nxt = jax.device_put(X[:bs]) if staging else X[:bs]
        for lo in range(0, n, bs):
            cur = nxt
            hi = min(lo + bs, n)
            if hi < n:
                # double-buffer: stage the NEXT batch before materializing
                # this batch's outputs (np.asarray below blocks on device)
                nxt = (
                    jax.device_put(X[hi : hi + bs])
                    if staging
                    else X[hi : hi + bs]
                )
            part = fn(cur)
            for k, v in part.items():
                chunks.setdefault(k, []).append(np.asarray(v)[: hi - lo])
        return {k: np.concatenate(v, axis=0) for k, v in chunks.items()}

    # ---- multi-model support (CV single-pass) ----------------------------
    @classmethod
    def _combine(cls, models: List["_TpuModel"]) -> "_TpuModel":
        raise NotImplementedError(f"{cls.__name__} does not support _combine")

    def _transformEvaluate(self, dataset: DataFrame, evaluator: Any) -> List[float]:
        raise NotImplementedError(
            f"{type(self).__name__} does not support _transformEvaluate"
        )

    # ---- persistence -----------------------------------------------------
    def write(self) -> "_Writer":
        return _Writer(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def read(cls) -> "_Reader":
        return _Reader(cls)

    @classmethod
    def load(cls, path: str) -> "_TpuModel":
        return cls.read().load(path)

    def cpu(self) -> "_TpuModel":
        """The reference converts to a Spark JVM model (``feature.py:365-379``);
        Spark-free, the model already runs on CPU via jax — return self. For
        serving *outside* this framework entirely, :meth:`to_sklearn` exports
        a stock fitted scikit-learn estimator."""
        return self

    def to_sklearn(self):
        """Export to a fitted scikit-learn estimator (accelerator-free
        serving; the analog of the reference's Spark-model conversion in
        ``cpu()``). See :mod:`spark_rapids_ml_tpu.export`."""
        from .export import to_sklearn

        return to_sklearn(self)


class _TpuModelWithPredictionCol(_TpuModel, HasPredictionCol):
    pass


# ---------------------------------------------------------------------------
# Persistence (reference ``core.py:244-331``): metadata JSON + npz arrays.
# ---------------------------------------------------------------------------


class _Writer:
    def __init__(self, instance: Union[_TpuEstimator, _TpuModel]):
        self._instance = instance
        self._overwrite = False

    def overwrite(self) -> "_Writer":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        inst = self._instance
        if os.path.exists(path):
            if self._overwrite:
                shutil.rmtree(path)
            else:
                raise FileExistsError(f"Path {path} exists; use write().overwrite()")
        os.makedirs(path)
        params = {}
        for p in inst.params:
            if inst.isSet(p):
                v = inst.getOrDefault(p)
                params[p.name] = v if _json_ok(v) else str(v)
        defaults = {}
        for p in inst.params:
            if inst.hasDefault(p):
                v = inst._defaultParamMap[p]
                defaults[p.name] = v if _json_ok(v) else str(v)
        meta = {
            "class": f"{type(inst).__module__}.{type(inst).__name__}",
            "uid": inst.uid,
            "paramMap": params,
            "defaultParamMap": defaults,
            "tpuParams": {k: v for k, v in inst._tpu_params.items() if _json_ok(v)},
            "numWorkers": inst._num_workers,
            "float32Inputs": inst._float32_inputs,
            "streaming": inst._streaming,
            "streamChunkRows": inst._stream_chunk_rows,
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        attrs = inst._get_model_attributes()
        if attrs is not None:
            arrays = {}
            scalars = {}
            for k, v in attrs.items():
                a = np.asarray(v)
                if a.dtype == object:
                    scalars[k] = v
                elif a.ndim == 0 and _json_ok(v):
                    scalars[k] = v if not isinstance(v, np.generic) else v.item()
                else:
                    arrays[k] = a
            if arrays:
                np.savez(os.path.join(path, "model.npz"), **arrays)
            with open(os.path.join(path, "attributes.json"), "w") as f:
                json.dump(scalars, f, indent=2, default=str)


class _Reader:
    def __init__(self, cls: type):
        self._cls = cls

    def load(self, path: str) -> Any:
        import importlib

        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        module_name, cls_name = meta["class"].rsplit(".", 1)
        module = importlib.import_module(module_name)
        cls = getattr(module, cls_name)

        attrs: Dict[str, Any] = {}
        npz_path = os.path.join(path, "model.npz")
        if os.path.exists(npz_path):
            with np.load(npz_path, allow_pickle=False) as z:
                attrs.update({k: z[k] for k in z.files})
        attrs_json = os.path.join(path, "attributes.json")
        if os.path.exists(attrs_json):
            with open(attrs_json) as f:
                attrs.update(json.load(f))

        if issubclass(cls, _TpuModel):
            inst = cls(**attrs)
        else:
            inst = cls()
        for name, v in meta.get("paramMap", {}).items():
            if inst.hasParam(name):
                inst._set(**{name: v})
        inst._tpu_params.update(meta.get("tpuParams", {}))
        inst._num_workers = meta.get("numWorkers")
        inst._float32_inputs = meta.get("float32Inputs", True)
        inst._streaming = meta.get("streaming")
        inst._stream_chunk_rows = meta.get("streamChunkRows")
        return inst


def _json_ok(v: Any) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False
