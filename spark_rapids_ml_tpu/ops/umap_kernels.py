"""UMAP device kernels: fuzzy simplicial set + edge-list SGD embedding.

TPU-native replacement for cuML's UMAP (the reference wraps it at
``/root/reference/python/src/spark_rapids_ml/umap.py:959-1077``; fit is
single-node there — coalesce(1) — so the graph build here runs on the host
with scipy.sparse and only the hot loops are device code):

* ``smooth_knn_dist`` — the per-point (rho, sigma) binary search, fully
  vectorized (64 fixed halving steps, no data-dependent control flow);
* ``optimize_embedding`` — the negative-sampling SGD. umap-learn applies
  per-edge updates asynchronously with an epochs_per_sample schedule; the
  XLA formulation does per-epoch *batched* updates: a Bernoulli edge mask
  (p = w/w_max, the same expected sampling rate), gathered endpoint
  embeddings, attractive/repulsive gradient math, and segment-sum
  scatter-adds — one ``lax.fori_loop`` over epochs, zero host round-trips.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

_SMOOTH_K_TOLERANCE = 1e-5
_MIN_K_DIST_SCALE = 1e-3


def find_ab_params(spread: float, min_dist: float) -> Tuple[float, float]:
    """Fit the (a, b) differentiable-curve params (umap-learn convention)."""
    from scipy.optimize import curve_fit

    def curve(x, a, b):
        return 1.0 / (1.0 + a * x ** (2 * b))

    xv = np.linspace(0, spread * 3, 300)
    yv = np.zeros(xv.shape)
    yv[xv < min_dist] = 1.0
    yv[xv >= min_dist] = np.exp(-(xv[xv >= min_dist] - min_dist) / spread)
    params, _ = curve_fit(curve, xv, yv)
    return float(params[0]), float(params[1])


@functools.partial(jax.jit, static_argnames=("local_connectivity", "n_iter"))
def smooth_knn_dist(
    knn_dists: jax.Array,  # (n, k) ascending neighbor distances (self excluded)
    local_connectivity: float,
    *,
    n_iter: int = 64,
) -> Tuple[jax.Array, jax.Array]:
    """Per-point (rho, sigma): rho = distance to the local_connectivity-th
    neighbor (interpolated), sigma solves sum exp(-(d-rho)/sigma) = log2(k)."""
    n, k = knn_dists.shape
    target = jnp.log2(jnp.asarray(float(k)))

    idx = int(np.floor(local_connectivity)) - 1
    frac = float(local_connectivity) - int(np.floor(local_connectivity))
    idx = max(idx, 0)
    rho = knn_dists[:, min(idx, k - 1)]
    if frac > 0 and idx + 1 < k:
        rho = rho + frac * (knn_dists[:, idx + 1] - knn_dists[:, idx])

    def psum_of(sigma):
        d = jnp.maximum(knn_dists - rho[:, None], 0.0)
        return jnp.exp(-d / sigma[:, None]).sum(axis=1)

    def body(_, state):
        lo, hi, mid = state
        val = psum_of(mid)
        too_high = val > target
        hi = jnp.where(too_high, mid, hi)
        lo = jnp.where(too_high, lo, mid)
        new_mid = jnp.where(
            jnp.isinf(hi), lo * 2.0, (lo + hi) / 2.0
        )
        return lo, hi, new_mid

    lo = jnp.zeros((n,), knn_dists.dtype)
    hi = jnp.full((n,), jnp.inf, knn_dists.dtype)
    mid = jnp.ones((n,), knn_dists.dtype)
    _, _, sigma = lax.fori_loop(0, n_iter, body, (lo, hi, mid))

    # floor sigma like umap-learn: never below MIN_K_DIST_SCALE * mean dist
    mean_d = jnp.maximum(knn_dists.mean(), 1e-12)
    sigma = jnp.maximum(sigma, _MIN_K_DIST_SCALE * mean_d)
    return rho, sigma


@jax.jit
def membership_strengths(
    knn_dists: jax.Array, rho: jax.Array, sigma: jax.Array
) -> jax.Array:
    """Directed fuzzy-set weights w_ij = exp(-max(0, d - rho_i)/sigma_i)."""
    d = jnp.maximum(knn_dists - rho[:, None], 0.0)
    return jnp.exp(-d / sigma[:, None])


def fuzzy_simplicial_set(
    knn_indices: np.ndarray,  # (n, k) neighbor row ids (self excluded)
    knn_dists: np.ndarray,
    local_connectivity: float,
    set_op_mix_ratio: float,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Symmetrized edge list (heads, tails, weights). Host scipy sparse:
    the structure is (n*k) edges — tiny next to the SGD — and sparse
    transpose-matching is a host-shaped op."""
    import scipy.sparse as sp

    n, k = knn_indices.shape
    rho, sigma = smooth_knn_dist(jnp.asarray(knn_dists), local_connectivity)
    w = np.asarray(membership_strengths(jnp.asarray(knn_dists), rho, sigma))

    rows = np.repeat(np.arange(n), k)
    cols = knn_indices.reshape(-1)
    A = sp.coo_matrix((w.reshape(-1), (rows, cols)), shape=(n, n)).tocsr()
    return _fuzzy_union_edges(A, set_op_mix_ratio)


def _fuzzy_union_edges(A, set_op_mix_ratio: float = 1.0):
    """Symmetrize a directed membership CSR via the probabilistic t-conorm
    (mixed with the intersection per ``set_op_mix_ratio``) and extract the
    positive-weight edge list."""
    T = A.T.tocsr()
    prod = A.multiply(T)
    sym = (
        set_op_mix_ratio * (A + T - prod) + (1.0 - set_op_mix_ratio) * prod
    ).tocoo()
    mask = sym.data > 0
    return (
        sym.row[mask].astype(np.int32),
        sym.col[mask].astype(np.int32),
        sym.data[mask].astype(np.float32),
    )


def categorical_simplicial_set_intersection(
    heads: np.ndarray,
    tails: np.ndarray,
    weights: np.ndarray,
    labels: np.ndarray,
    n: int,
    far_dist: float = 5.0,
    unknown_dist: float = 1.0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Supervised (categorical) intersection of the fuzzy simplicial set
    with a label-induced set — the standard UMAP supervision the reference
    gets from cuML's ``fit(X, y=labels)`` (``umap.py:941-947``; cuML
    default ``target_weight=0.5`` ⇒ ``far_dist = 2.5/(1-0.5) = 5``).

    Edges joining different labels are scaled by exp(-far_dist), edges
    with an unknown (< 0) endpoint by exp(-unknown_dist); local
    connectivity is then reset (per-row max normalization + fuzzy union),
    restoring each point's strongest link to weight ~1.
    """
    import scipy.sparse as sp

    li = labels[heads]
    lj = labels[tails]
    unknown = (li < 0) | (lj < 0)
    diff = (li != lj) & ~unknown
    scale = np.where(
        unknown, np.exp(-unknown_dist), np.where(diff, np.exp(-far_dist), 1.0)
    )
    w = weights * scale

    A = sp.coo_matrix((w, (heads, tails)), shape=(n, n)).tocsr()
    rowmax = np.asarray(A.max(axis=1).todense()).ravel()
    A = sp.diags(1.0 / np.maximum(rowmax, 1e-12)) @ A
    return _fuzzy_union_edges(A)


def spectral_init(
    heads: np.ndarray, tails: np.ndarray, weights: np.ndarray, n: int,
    n_components: int, seed: int,
) -> np.ndarray:
    """Normalized-Laplacian spectral layout (umap 'init=spectral'); falls
    back to random on solver failure."""
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    try:
        graph = sp.coo_matrix((weights, (heads, tails)), shape=(n, n)).tocsr()
        diag = np.asarray(graph.sum(axis=1)).ravel()
        d_inv_sqrt = 1.0 / np.sqrt(np.maximum(diag, 1e-12))
        D = sp.diags(d_inv_sqrt)
        from scipy.sparse.linalg import eigsh

        # Smallest eigenpairs of the normalized Laplacian L = I - D·G·D via
        # plain Lanczos on the spectrum-flipped operator 2I - L = I + D·G·D
        # (L's spectrum lies in [0, 2], so its smallest become the flipped
        # operator's largest-magnitude). NOT shift-invert (sigma=0): that
        # sparse-LU-factorizes L, whose kNN-graph fill-in scales brutally
        # (measured 34 s at n=4096, 217 s at n=8192 vs 0.4/0.7 s flipped —
        # it dominated UMAP fits).
        k = n_components + 1
        flip_vals, vecs = eigsh(
            sp.identity(n) + D @ graph @ D, k=k, which="LM", maxiter=n * 5
        )
        order = np.argsort(2.0 - flip_vals)   # ascending eigenvalues of L
        emb = vecs[:, order[1 : n_components + 1]]
        expansion = 10.0 / np.maximum(np.abs(emb).max(), 1e-12)
        return (emb * expansion).astype(np.float32) + rng.normal(
            scale=1e-4, size=(n, n_components)
        ).astype(np.float32)
    except Exception:
        return rng.uniform(-10, 10, size=(n, n_components)).astype(np.float32)


@functools.partial(
    jax.jit,
    static_argnames=("n_epochs", "negative_sample_rate", "move_other", "n_vertices"),
)
def optimize_embedding(
    emb_head: jax.Array,    # (n_head, c) embedding being optimized
    emb_tail: jax.Array,    # (n_tail, c) reference embedding (== emb_head for fit)
    heads: jax.Array,       # (m,) int32
    tails: jax.Array,       # (m,) int32
    weights: jax.Array,     # (m,) float32
    key: jax.Array,
    *,
    n_epochs: int,
    n_vertices: int,        # tail vertex count for negative sampling
    a: float,
    b: float,
    gamma: float = 1.0,
    initial_alpha: float = 1.0,
    negative_sample_rate: int = 5,
    move_other: bool = True,
) -> jax.Array:
    """Batched-per-epoch negative-sampling SGD (see module docstring)."""
    m = heads.shape[0]
    n_head = emb_head.shape[0]
    p_edge = weights / jnp.maximum(weights.max(), 1e-12)
    neg = int(negative_sample_rate)

    def clip4(x):
        return jnp.clip(x, -4.0, 4.0)

    def epoch(e, state):
        emb, emb_t = state
        # fit mode (move_other): tails live in the SAME evolving embedding;
        # transform mode: tails are the frozen training embedding
        src = emb if move_other else emb_t
        k1, k2 = jax.random.split(jax.random.fold_in(key, e))
        alpha = initial_alpha * (1.0 - e / n_epochs)
        active = (jax.random.uniform(k1, (m,)) < p_edge).astype(emb.dtype)

        h = emb[heads]                       # (m, c)
        t = src[tails]
        diff = h - t
        d2 = (diff * diff).sum(axis=1)
        # attractive: -2ab d^{2(b-1)} / (1 + a d^{2b})
        ac = (-2.0 * a * b * d2 ** (b - 1.0)) / (a * d2**b + 1.0)
        ac = jnp.where(d2 > 0.0, ac, 0.0) * active
        grad_h = clip4(ac[:, None] * diff)
        upd = jax.ops.segment_sum(grad_h, heads, num_segments=n_head)
        if move_other:
            upd = upd - jax.ops.segment_sum(grad_h, tails, num_segments=n_head)

        # repulsive: neg random tail samples per active edge
        neg_idx = jax.random.randint(k2, (m, neg), 0, n_vertices)
        tn = src[neg_idx]                    # (m, neg, c)
        diff_n = h[:, None, :] - tn
        d2n = (diff_n * diff_n).sum(axis=2)
        rc = (2.0 * gamma * b) / ((0.001 + d2n) * (a * d2n**b + 1.0))
        rc = jnp.where(d2n > 0.0, rc, 0.0) * active[:, None]
        grad_n = clip4(rc[:, :, None] * diff_n).sum(axis=1)
        upd = upd + jax.ops.segment_sum(grad_n, heads, num_segments=n_head)

        emb = emb + alpha * upd
        return emb, emb_t

    emb, _ = lax.fori_loop(0, n_epochs, epoch, (emb_head, emb_tail))
    return emb


def default_n_epochs(n: int) -> int:
    return 500 if n <= 10000 else 200
